"""Mutable shared-memory channels (seqlock + per-reader acks).

Parity: reference experimental mutable plasma objects + shm channels
(`src/ray/core_worker/experimental_mutable_object_manager.h:44`,
`python/ray/experimental/channel/shared_memory_channel.py`) — the data
plane under Compiled Graphs. One writer, a fixed set of readers; a version
seqlock (odd = write in progress) makes reads lock-free, and per-reader ack
slots give the writer backpressure (it blocks until every reader consumed
the previous value — same flow control as the reference's mutable-object
WriteAcquire waiting on ReadRelease). Same-node only (the region is a
/dev/shm mmap); cross-node edges belong to the object plane.

Layout: [u64 version][u64 payload_len][u64 n_readers][u64 ack x 8][payload]
Each ack slot is written by exactly one reader (its last-read version), so
there are no cross-process read-modify-write races.

Two payload encodings share the seqlock:

- `Channel` — pickle the whole value (control values, small objects).
- `TensorChannel` — the zero-copy tensor plane (parity: the role NCCL
  channels play under the reference's compiled graphs,
  `torch_tensor_nccl_channel.py` / `nccl_group.py:22`, rebuilt for host
  shm + TPU): array leaves of the value are staged STRAIGHT into the shm
  region (one memcpy, multi-threaded native memcpy for large leaves)
  under a fixed binary descriptor (dtype/shape/sharding spec) — tensor
  bytes never pass through pickle; only the pytree skeleton rides a
  sidecar pickle frame. Readers rebuild jax leaves with `jax.device_put`
  (the one host->device copy) and hand numpy leaves out as read-only
  views that alias the channel (ack deferred until `release()` — the
  reference's ReadAcquire/ReadRelease). A same-process registry lets
  co-located writer/reader pairs hand over the live `jax.Array`
  reference with no host round-trip at all, guarded by a copy-on-write
  epoch in the frame header. For cross-NODE hops the same frame seals
  into the shm arena as a plain object (`put_tensor_object`) and the
  remote side pulls it over `objxfer` then `device_put`s
  (`get_tensor_object`).
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
import sys
import threading
import time
import uuid

from ray_tpu.core import task_events as _task_events

# Process-global emission ring: TensorChannel write / read-acquire spans
# land in the task-event pipeline (one flag check when it is off).
_TEV = _task_events.ring()

MAX_READERS = 8
_HDR = struct.Struct(f"<QQQ{MAX_READERS}Q")

_CLOSE = b"\x00__ray_tpu_channel_closed__"


class ChannelClosedError(RuntimeError):
    pass


class Channel:
    """One writer, n_readers consumers. The writer constructs with
    create=True; each reader opens a cursor with its assigned reader_idx."""

    def __init__(self, path: str | None = None, capacity: int = 1 << 20,
                 create: bool = False, n_readers: int = 1,
                 reader_idx: int = 0):
        if n_readers > MAX_READERS:
            raise ValueError(f"at most {MAX_READERS} readers per channel")
        shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"
        self.path = path or os.path.join(
            shm_dir, f"ray_tpu_chan_{uuid.uuid4().hex[:16]}")
        self.capacity = capacity
        self.reader_idx = reader_idx
        total = _HDR.size + capacity
        if create:
            fd = os.open(self.path, os.O_CREAT | os.O_RDWR | os.O_EXCL,
                         0o600)
            os.ftruncate(fd, total)
        else:
            fd = os.open(self.path, os.O_RDWR)
            total = os.fstat(fd).st_size
            self.capacity = total - _HDR.size
        try:
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        if create:
            struct.pack_into("<Q", self._mm, 16, n_readers)
        self._last_version = 0

    def _hdr(self):
        vals = _HDR.unpack_from(self._mm, 0)
        return vals[0], vals[1], vals[2], vals[3:3 + MAX_READERS]

    # -- writer side --

    def _begin_write(self, length: int, timeout: float | None) -> int:
        """Win backpressure and mark the seqlock odd (write in progress).
        Returns the pre-write version; the caller stages the payload into
        the region after the header and then calls `_commit_write`."""
        if length > self.capacity:
            raise ValueError(
                f"value of {length} bytes exceeds channel capacity "
                f"{self.capacity}")
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 5e-5
        while True:  # backpressure: all readers must have consumed
            version, _, n_readers, acks = self._hdr()
            if version == 0 or all(a >= version
                                   for a in acks[:n_readers]):
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"channel write blocked on slow readers ({self.path})")
            time.sleep(delay)
            delay = min(delay * 2, 1e-3)
        struct.pack_into("<Q", self._mm, 0, version + 1)  # odd: writing
        return version

    def _commit_write(self, version: int, length: int):
        struct.pack_into("<QQ", self._mm, 0, version + 2, length)

    def write(self, value, timeout: float | None = 60.0):
        self.write_bytes(pickle.dumps(value, protocol=5), timeout)

    def write_bytes(self, payload: bytes, timeout: float | None = 60.0):
        version = self._begin_write(len(payload), timeout)
        self._mm[_HDR.size:_HDR.size + len(payload)] = payload
        self._commit_write(version, len(payload))

    def close_writer(self, timeout: float | None = 10.0):
        """Signal EOF to readers. If a slow reader never acks within the
        timeout, FORCE the sentinel in (skipping backpressure): it may
        clobber the reader's last unread value, but a dropped EOF would
        leave exec loops busy-polling a dead channel forever."""
        try:
            self.write_bytes(_CLOSE, timeout)
            return
        except TimeoutError:
            pass
        except (ValueError, OSError):
            return
        try:
            version, _ = struct.unpack_from("<QQ", self._mm, 0)
            struct.pack_into("<Q", self._mm, 0, version + 1)
            self._mm[_HDR.size:_HDR.size + len(_CLOSE)] = _CLOSE
            struct.pack_into("<QQ", self._mm, 0, version + 2, len(_CLOSE))
        except (ValueError, OSError):
            pass

    # -- reader side --

    def _poll_version(self, timeout: float | None):
        """Block until a version newer than the cursor is committed;
        returns (version, length) without acking."""
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 5e-5
        while True:
            version, length, _n, _acks = self._hdr()
            if version > self._last_version and version % 2 == 0:
                return version, length
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel read timed out ({self.path})")
            time.sleep(delay)
            delay = min(delay * 2, 1e-3)

    def _ack(self, version: int):
        self._last_version = version
        struct.pack_into("<Q", self._mm, 24 + 8 * self.reader_idx, version)

    def _stable(self, version: int) -> bool:
        v2, = struct.unpack_from("<Q", self._mm, 0)
        return v2 == version

    def read(self, timeout: float | None = 60.0):
        """Block until a version newer than this cursor's last read; ack it
        so the writer may proceed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            version, length = self._poll_version(remaining)
            payload = bytes(self._mm[_HDR.size:_HDR.size + length])
            if self._stable(version):  # seqlock: no concurrent write seen
                self._ack(version)
                if payload == _CLOSE:
                    raise ChannelClosedError(self.path)
                return pickle.loads(payload)
            time.sleep(5e-5)

    # -- lifecycle --

    def close(self):
        try:
            self._mm.close()
        except BufferError:
            pass

    def unlink(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __reduce__(self):
        return (Channel, (self.path, self.capacity, False, 1,
                          self.reader_idx))


# ====================================================================
# Tensor channel: zero-copy jax/numpy hops for compiled graphs
# ====================================================================

# Frame layout (inside the seqlock payload region):
#   [_TC_HDR: magic, flags, epoch, writer_pid, n_leaves, meta_len]
#   [_TC_LEAF x n_leaves: dtype16, kind, ndim, dims[6], offset, nbytes]
#   [meta pickle bytes]                    (skeleton; NO tensor bytes)
#   [leaf payloads, 64-aligned offsets relative to the payload start]
#
# flags bit 0 (INPROC): leaf payloads and table are ABSENT — the whole
# value lives in the writer-process registry; only a reader in the
# writer's process may consume the frame (it receives the live object
# reference).

_TC_MAGIC = 0x31435452  # "RTC1"
_TC_HDR = struct.Struct("<IIQQII")
_TC_LEAF = struct.Struct("<16sBB6qQQ")
_TC_INPROC = 1
_TC_ALIGN = 64
_TC_MAX_DIMS = 6

_KIND_NP = 0
_KIND_JAX = 1

# Copies above this go through the native multi-threaded memcpy when the
# object-store native build is loadable (same thresholds as object_store).
_FAST_COPY_MIN = 256 << 10
_MT_COPY_MIN = 32 << 20


class _TensorRef:
    """Sidecar-pickle placeholder for an extracted tensor leaf. `spec` is
    an optional sharding spec (e.g. a PartitionSpec) the reader may apply
    when handed a mesh."""

    __slots__ = ("index", "spec")

    def __init__(self, index: int, spec=None):
        self.index = index
        self.spec = spec

    def __reduce__(self):
        return (_TensorRef, (self.index, self.spec))


class _InprocRegistry:
    """Process-local (path -> (version, epoch, value)) table backing the
    same-process fast path. Only the LATEST committed value is retained
    per channel, so the registry cannot grow beyond live channels."""

    def __init__(self):
        self._values: dict[str, tuple[int, int, object]] = {}
        self._lock = threading.Lock()

    def publish(self, path: str, version: int, epoch: int, value):
        with self._lock:
            self._values[path] = (version, epoch, value)

    def lookup(self, path: str, version: int, epoch: int):
        """Returns (hit, value). The copy-on-write epoch guard: a stale or
        force-overwritten entry (epoch/version mismatch) is a MISS, never
        the wrong value."""
        with self._lock:
            ent = self._values.get(path)
        if ent is None or ent[0] != version or ent[1] != epoch:
            return False, None
        return True, ent[2]

    def drop(self, path: str):
        with self._lock:
            self._values.pop(path, None)


_INPROC = _InprocRegistry()


def _leaf_kind(v):
    """_KIND_NP / _KIND_JAX for array leaves the tensor plane carries
    natively; None for everything else (rides the sidecar pickle)."""
    import numpy as np
    if isinstance(v, np.ndarray):
        return None if v.dtype.hasobject else _KIND_NP
    jax = sys.modules.get("jax")
    if jax is not None and isinstance(v, jax.Array):
        return _KIND_JAX
    return None


def _leaf_spec(v, kind):
    """Best-effort sharding spec of a jax leaf (PartitionSpec or None) —
    metadata only; the reader applies it iff it reconstructs onto a
    mesh."""
    if kind != _KIND_JAX:
        return None
    try:
        return getattr(v.sharding, "spec", None)
    except Exception:  # noqa: BLE001 — spec is advisory
        return None


def _host_view(v):
    """C-contiguous host ndarray of a leaf. For a jax leaf this is THE
    device->host transfer (exactly once per hop); on the CPU backend it
    aliases the device buffer (no copy)."""
    import numpy as np
    arr = np.asarray(v)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    return arr


def _extract(value, leaves, descs, threshold):
    """Recursively split container skeletons (dict/list/tuple) from array
    leaves. Array leaves >= threshold bytes and <= 6-D move to the binary
    plane; everything else stays in the sidecar pickle."""
    kind = _leaf_kind(value)
    if kind is not None:
        host = _host_view(value)
        if host.nbytes >= threshold and host.ndim <= _TC_MAX_DIMS:
            leaves.append(host)
            descs.append((kind, host.dtype.name, host.shape))
            return _TensorRef(len(leaves) - 1, _leaf_spec(value, kind))
        return value
    if isinstance(value, dict):
        return {k: _extract(v, leaves, descs, threshold)
                for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        t = [_extract(v, leaves, descs, threshold) for v in value]
        return t if isinstance(value, list) else tuple(t)
    return value


def _inline_threshold() -> int:
    try:
        from ray_tpu.core.config import get_config
        return get_config().tensor_channel_inline_bytes
    except Exception:  # noqa: BLE001 — config not importable (bare tests)
        return 4096


class _FramePlan:
    """One encoded-frame layout: header + leaf table + meta + payloads.

    `inproc=True` plans carry NO leaf table, meta, or payloads — the
    value is handed over through the process registry, so the host
    representation is never materialized at all."""

    __slots__ = ("meta", "leaves", "descs", "offsets", "total", "flags")

    def __init__(self, value, threshold: int, inproc: bool):
        if inproc:
            self.meta, self.leaves, self.descs, self.offsets = \
                b"", [], [], []
            self.flags = _TC_INPROC
            self.total = _TC_HDR.size
            return
        leaves: list = []
        descs: list = []
        skeleton = _extract(value, leaves, descs, threshold)
        self.meta = pickle.dumps(skeleton, protocol=5)
        self.leaves = leaves
        self.descs = descs
        self.flags = 0
        head = _TC_HDR.size + _TC_LEAF.size * len(leaves) + len(self.meta)
        off = head + ((-head) % _TC_ALIGN)
        self.offsets = []
        for leaf in leaves:
            self.offsets.append(off)
            off += leaf.nbytes + ((-leaf.nbytes) % _TC_ALIGN)
        self.total = off if leaves else head

    def encode_into(self, buf, base: int, epoch: int, copy_fn):
        """Write the frame into `buf` at byte offset `base`. `buf` must
        support struct.pack_into (mmap or writable memoryview);
        `copy_fn(off, arr)` stages one leaf payload at frame-relative
        offset `off` (the fast-memcpy hook)."""
        _TC_HDR.pack_into(buf, base, _TC_MAGIC, self.flags, epoch,
                          os.getpid(), len(self.leaves), len(self.meta))
        pos = base + _TC_HDR.size
        for (kind, dtype_name, shape), off, leaf in zip(
                self.descs, self.offsets, self.leaves):
            dims = list(shape) + [0] * (_TC_MAX_DIMS - len(shape))
            _TC_LEAF.pack_into(buf, pos, dtype_name.encode()[:16], kind,
                               len(shape), *dims, off, leaf.nbytes)
            pos += _TC_LEAF.size
        if self.meta:
            struct.pack_into(f"<{len(self.meta)}s", buf, pos, self.meta)
        for off, leaf in zip(self.offsets, self.leaves):
            if leaf.nbytes:
                copy_fn(off, leaf)


def frame_regions(buf, base: int = 0) -> dict:
    """Parse a tensor frame's layout WITHOUT materializing values — test
    leverage for the no-pickle plane assertion (the proto_wire
    `allow_pickle=False` pattern: the tensor plane must be provably
    pickle-free outside the declared meta region)."""
    magic, flags, epoch, pid, n_leaves, meta_len = _TC_HDR.unpack_from(
        buf, base)
    if magic != _TC_MAGIC:
        raise ValueError("not a tensor frame")
    leaves = []
    pos = base + _TC_HDR.size
    for _ in range(n_leaves):
        raw_dtype, kind, ndim, *rest = _TC_LEAF.unpack_from(buf, pos)
        dims, off, nbytes = rest[:_TC_MAX_DIMS], rest[-2], rest[-1]
        leaves.append({"dtype": raw_dtype.rstrip(b"\0").decode(),
                       "kind": kind, "shape": tuple(dims[:ndim]),
                       "offset": off, "nbytes": nbytes})
        pos += _TC_LEAF.size
    return {"flags": flags, "epoch": epoch, "writer_pid": pid,
            "meta_offset": pos - base, "meta_len": meta_len,
            "leaves": leaves}


def _np_dtype(name: str):
    import numpy as np
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: F401 — registers bfloat16 et al.
        return np.dtype(name)


def _decode_frame(buf, base: int, *, copy_np: bool, mesh=None):
    """Rebuild the value from a tensor frame at `buf[base:]`.

    numpy leaves alias `buf` as read-only views when copy_np=False (the
    caller owns the release discipline); jax leaves are `jax.device_put`
    — the single host->device copy of the hop — and BLOCKED until the
    transfer lands, so the source region may be reused immediately after
    this returns. Returns (value, borrowed)."""
    import numpy as np
    info = frame_regions(buf, base)
    meta_off = base + info["meta_offset"]
    skeleton = pickle.loads(bytes(memoryview(buf)[
        meta_off:meta_off + info["meta_len"]]))
    arrays = []
    for leaf in info["leaves"]:
        view = np.frombuffer(buf, dtype=np.uint8, count=leaf["nbytes"],
                             offset=base + leaf["offset"])
        arr = view.view(_np_dtype(leaf["dtype"])).reshape(leaf["shape"])
        arr.flags.writeable = False
        arrays.append((leaf["kind"], arr))

    jax_outs: list = []

    def resolve(node):
        if isinstance(node, _TensorRef):
            kind, arr = arrays[node.index]
            if kind == _KIND_JAX:
                import jax
                if mesh is not None and node.spec is not None:
                    from jax.sharding import NamedSharding
                    out = jax.device_put(
                        arr, NamedSharding(mesh, node.spec))
                else:
                    out = jax.device_put(arr)
                jax_outs.append(out)
                return out
            return arr.copy() if copy_np else arr
        if isinstance(node, dict):
            return {k: resolve(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            t = [resolve(v) for v in node]
            return t if isinstance(node, list) else tuple(t)
        return node

    value = resolve(skeleton)
    if jax_outs:
        import jax
        # device_put is async: the writer may overwrite the source the
        # moment we ack, so the transfers must have landed first.
        jax.block_until_ready(jax_outs)
    borrowed = (not copy_np) and any(k == _KIND_NP for k, _ in arrays)
    return value, borrowed


class TensorChannel(Channel):
    """Seqlock channel whose payload is the tensor frame above.

    Writer: `write(value)` stages array leaves straight into the shm
    region (one memcpy; multi-threaded native memcpy for >=32MB leaves)
    and publishes the live value in the process-local registry for
    same-process readers.

    Reader: `read()` returns the value. jax leaves arrive as fresh device
    arrays (safe to hold); numpy leaves arrive as READ-ONLY views aliasing
    the channel — the ack is deferred until `release()` (or the next
    read/close), which is when the writer may overwrite. Pass copy=True to
    materialize numpy leaves and ack immediately.

    `inproc=True` (writer side) skips the host representation entirely:
    the frame carries only the 32-byte header, and readers MUST be in the
    writer's process (they receive the live object reference — zero
    copies, zero host round-trips; do not mutate handed-over numpy leaves
    in place). The copy-on-write epoch in the header guards the hand-off:
    a reader never resolves a registry value from a different write than
    the version its seqlock read committed."""

    def __init__(self, path: str | None = None, capacity: int = 1 << 20,
                 create: bool = False, n_readers: int = 1,
                 reader_idx: int = 0, inproc: bool = False):
        super().__init__(path, capacity, create=create,
                         n_readers=n_readers, reader_idx=reader_idx)
        self.inproc = inproc
        self._epoch = 0
        self._pending_ack: int | None = None
        self._native = None  # lazily probed (lib, mm_base_addr) | (None, 0)

    # -- native fast copy --

    def _native_copy(self):
        if self._native is None:
            try:
                import ctypes
                from ray_tpu.core.object_store import _lib
                lib = _lib()
                base = ctypes.addressof(
                    ctypes.c_char.from_buffer(self._mm))
                self._native = (lib, base)
            except Exception:  # noqa: BLE001 — no toolchain: plain copies
                self._native = (None, 0)
        return self._native

    def _copy_leaf(self, off: int, leaf):
        import numpy as np
        abs_off = _HDR.size + off
        n = leaf.nbytes
        lib = None
        if n >= _FAST_COPY_MIN:
            lib, base = self._native_copy()
        if lib is not None:
            import ctypes
            threads = (min(8, os.cpu_count() or 1)
                       if n >= _MT_COPY_MIN else 1)
            lib.store_memcpy(ctypes.c_void_p(base + abs_off),
                             ctypes.c_void_p(leaf.ctypes.data), n, threads)
        else:
            memoryview(self._mm)[abs_off:abs_off + n] = \
                leaf.reshape(-1).view(np.uint8)

    # -- writer side --

    def write(self, value, timeout: float | None = 60.0):
        t0 = time.time() if _TEV.enabled else 0.0
        plan = _FramePlan(value, _inline_threshold(), self.inproc)
        version = self._begin_write(plan.total, timeout)
        self._epoch += 1
        plan.encode_into(self._mm, _HDR.size, self._epoch, self._copy_leaf)
        # Publish BEFORE commit: once a reader can observe the version,
        # the registry entry for it already exists.
        _INPROC.publish(self.path, version + 2, self._epoch, value)
        self._commit_write(version, plan.total)
        if _TEV.enabled:
            _TEV.emit_span("chan_write", os.path.basename(self.path), t0,
                           time.time() - t0, bytes=plan.total)

    # -- reader side --

    def release(self):
        """Ack a borrowed read (numpy views handed out by the last
        `read(copy=False)`); the writer may then overwrite the region.
        Views obtained from that read MUST NOT be used afterwards."""
        if self._pending_ack is not None:
            v, self._pending_ack = self._pending_ack, None
            self._ack(v)

    def read(self, timeout: float | None = 60.0, *, copy: bool = False,
             mesh=None):
        self.release()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            version, length = self._poll_version(remaining)
            t0 = time.time() if _TEV.enabled else 0.0
            result = self._try_decode(version, length, copy, mesh)
            if result is not None:
                if _TEV.enabled:
                    # Read-ACQUIRE cost only (decode + device_put of the
                    # committed frame), not the wait for the writer.
                    _TEV.emit_span("chan_read",
                                   os.path.basename(self.path), t0,
                                   time.time() - t0, bytes=length)
                return result[0]
            time.sleep(5e-5)

    def _try_decode(self, version, length, copy, mesh):
        """One seqlock-guarded decode attempt; None = torn read, retry."""
        if length == len(_CLOSE) and \
                self._mm[_HDR.size:_HDR.size + length] == _CLOSE:
            if not self._stable(version):
                return None
            self._ack(version)
            raise ChannelClosedError(self.path)
        try:
            info = frame_regions(self._mm, _HDR.size)
        except (ValueError, struct.error):
            if self._stable(version):
                raise
            return None  # torn header mid-overwrite
        if info["writer_pid"] == os.getpid():
            # Same-process fast path: hand over the live reference. The
            # epoch guard rejects a registry slot replaced by a newer
            # (or forced) write after this version was committed.
            hit, value = _INPROC.lookup(self.path, version, info["epoch"])
            if hit:
                if not self._stable(version):
                    return None
                self._ack(version)
                return (value,)
        if info["flags"] & _TC_INPROC:
            if not self._stable(version):
                return None  # mid-overwrite: stale header, retry
            raise RuntimeError(
                f"in-proc tensor channel {self.path} read from pid "
                f"{os.getpid()} (writer pid {info['writer_pid']}): "
                "create the channel with inproc=False for cross-process "
                "readers")
        try:
            value, borrowed = _decode_frame(self._mm, _HDR.size,
                                            copy_np=copy, mesh=mesh)
        except Exception:  # noqa: BLE001 — garbage from a torn frame
            if self._stable(version):
                raise
            return None
        if not self._stable(version):
            return None
        if borrowed:
            # numpy views alias the channel: hold the ack until release()
            # so the writer cannot overwrite under the reader.
            self._last_version = version
            self._pending_ack = version
        else:
            self._ack(version)
        return (value,)

    # -- lifecycle --

    def close(self):
        self.release()
        if self._epoch:  # this cursor was the writer
            _INPROC.drop(self.path)
        super().close()

    def __reduce__(self):
        return (TensorChannel, (self.path, self.capacity, False, 1,
                                self.reader_idx, self.inproc))


# -------------------- object-plane (cross-node) hops --------------------


def put_tensor_object(store, value, object_id=None):
    """Seal `value` as ONE shm-arena object in tensor-frame encoding and
    return its ObjectID. The cross-node half of the tensor plane: a remote
    reader pulls the sealed object over `objxfer.fetch_from_peer` into its
    own arena and rebuilds with `get_tensor_object` — the activation bytes
    cross the wire once, with no pickle on either side's tensor leaves."""
    from ray_tpu.core.ids import ObjectID
    if object_id is None:
        object_id = ObjectID.from_random()
    plan = _FramePlan(value, _inline_threshold(), inproc=False)
    buf = store._acquire_buffer(object_id, plan.total, meta=b"tensor_frame")
    try:
        import ctypes

        def copy_fn(off, leaf):
            n = leaf.nbytes
            if n >= _FAST_COPY_MIN:
                if n >= _MT_COPY_MIN:
                    # Thread budget shared with every concurrent arena
                    # copier (shm counter) — see store_copy_adaptive.
                    store._lib.store_copy_adaptive(
                        store._base,
                        ctypes.c_void_p(store._base + buf.offset + off),
                        ctypes.c_void_p(leaf.ctypes.data), n,
                        min(8, os.cpu_count() or 1))
                    return
                store._lib.store_memcpy(
                    ctypes.c_void_p(store._base + buf.offset + off),
                    ctypes.c_void_p(leaf.ctypes.data), n, 1)
            else:
                import numpy as np
                buf.data[off:off + n] = leaf.reshape(-1).view(np.uint8)

        plan.encode_into(buf.data, 0, 1, copy_fn)
        buf.seal()
    except BaseException:
        buf.abort()
        raise
    return object_id


def get_tensor_object(store, object_id, timeout: float | None = None,
                      mesh=None):
    """Rebuild a `put_tensor_object` value from the local arena. jax
    leaves are device_put (the one host->device copy); numpy leaves are
    copied out so the store reference can be released immediately."""
    res = store.get_raw(object_id, timeout)
    if res is None:
        raise KeyError(f"tensor object {object_id} not found")
    data, _meta = res
    try:
        value, _ = _decode_frame(data, 0, copy_np=True, mesh=mesh)
    finally:
        try:
            data.release()
        except BufferError:
            pass  # a transient frombuffer view; dies with this frame
        store.release(object_id)
    return value


def __graphcheck__(gc):
    """graphcheck hook (tools/graphcheck): the TensorChannel read-side
    restage — the `jax.device_put` that rebuilds device leaves from the
    shm frame. Pins that the path stays a pure host->device copy: zero
    collectives, zero host callbacks (a stray debug hook here would
    serialize every channel read)."""

    def build(mesh):
        import jax
        import jax.numpy as jnp

        leaves = {"acts": jax.ShapeDtypeStruct((64, 256), jnp.float32),
                  "tokens": jax.ShapeDtypeStruct((64,), jnp.int32)}

        def restage(frame):
            return jax.tree_util.tree_map(jax.device_put, frame)

        return gc.GraphSpec(
            name="channel.device_put", fn=restage, args=(leaves,),
            min_donate_bytes=16384, arg_names=("frame",))

    # The rebuilt device arrays are copies BY DESIGN: the inputs alias
    # the mmap'd channel region (or a borrowed reader view), which the
    # writer will overwrite after the ack — donating them would hand XLA
    # a buffer the seqlock protocol still owns.
    # graphcheck: ok donation-missing — reader must not overwrite the
    # borrowed channel region; restage output is a deliberate copy.
    gc.register("channel.device_put", build)
