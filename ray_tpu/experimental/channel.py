"""Mutable shared-memory channels (seqlock + per-reader acks).

Parity: reference experimental mutable plasma objects + shm channels
(`src/ray/core_worker/experimental_mutable_object_manager.h:44`,
`python/ray/experimental/channel/shared_memory_channel.py`) — the data
plane under Compiled Graphs. One writer, a fixed set of readers; a version
seqlock (odd = write in progress) makes reads lock-free, and per-reader ack
slots give the writer backpressure (it blocks until every reader consumed
the previous value — same flow control as the reference's mutable-object
WriteAcquire waiting on ReadRelease). Same-node only (the region is a
/dev/shm mmap); cross-node edges belong to the object plane.

Layout: [u64 version][u64 payload_len][u64 n_readers][u64 ack x 8][payload]
Each ack slot is written by exactly one reader (its last-read version), so
there are no cross-process read-modify-write races.
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
import time
import uuid

MAX_READERS = 8
_HDR = struct.Struct(f"<QQQ{MAX_READERS}Q")

_CLOSE = b"\x00__ray_tpu_channel_closed__"


class ChannelClosedError(RuntimeError):
    pass


class Channel:
    """One writer, n_readers consumers. The writer constructs with
    create=True; each reader opens a cursor with its assigned reader_idx."""

    def __init__(self, path: str | None = None, capacity: int = 1 << 20,
                 create: bool = False, n_readers: int = 1,
                 reader_idx: int = 0):
        if n_readers > MAX_READERS:
            raise ValueError(f"at most {MAX_READERS} readers per channel")
        shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"
        self.path = path or os.path.join(
            shm_dir, f"ray_tpu_chan_{uuid.uuid4().hex[:16]}")
        self.capacity = capacity
        self.reader_idx = reader_idx
        total = _HDR.size + capacity
        if create:
            fd = os.open(self.path, os.O_CREAT | os.O_RDWR | os.O_EXCL,
                         0o600)
            os.ftruncate(fd, total)
        else:
            fd = os.open(self.path, os.O_RDWR)
            total = os.fstat(fd).st_size
            self.capacity = total - _HDR.size
        try:
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        if create:
            struct.pack_into("<Q", self._mm, 16, n_readers)
        self._last_version = 0

    def _hdr(self):
        vals = _HDR.unpack_from(self._mm, 0)
        return vals[0], vals[1], vals[2], vals[3:3 + MAX_READERS]

    # -- writer side --

    def write(self, value, timeout: float | None = 60.0):
        self.write_bytes(pickle.dumps(value, protocol=5), timeout)

    def write_bytes(self, payload: bytes, timeout: float | None = 60.0):
        if len(payload) > self.capacity:
            raise ValueError(
                f"value of {len(payload)} bytes exceeds channel capacity "
                f"{self.capacity}")
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 5e-5
        while True:  # backpressure: all readers must have consumed
            version, _, n_readers, acks = self._hdr()
            if version == 0 or all(a >= version
                                   for a in acks[:n_readers]):
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"channel write blocked on slow readers ({self.path})")
            time.sleep(delay)
            delay = min(delay * 2, 1e-3)
        struct.pack_into("<Q", self._mm, 0, version + 1)  # odd: writing
        self._mm[_HDR.size:_HDR.size + len(payload)] = payload
        struct.pack_into("<QQ", self._mm, 0, version + 2, len(payload))

    def close_writer(self, timeout: float | None = 10.0):
        """Signal EOF to readers. If a slow reader never acks within the
        timeout, FORCE the sentinel in (skipping backpressure): it may
        clobber the reader's last unread value, but a dropped EOF would
        leave exec loops busy-polling a dead channel forever."""
        try:
            self.write_bytes(_CLOSE, timeout)
            return
        except TimeoutError:
            pass
        except (ValueError, OSError):
            return
        try:
            version, _ = struct.unpack_from("<QQ", self._mm, 0)
            struct.pack_into("<Q", self._mm, 0, version + 1)
            self._mm[_HDR.size:_HDR.size + len(_CLOSE)] = _CLOSE
            struct.pack_into("<QQ", self._mm, 0, version + 2, len(_CLOSE))
        except (ValueError, OSError):
            pass

    # -- reader side --

    def read(self, timeout: float | None = 60.0):
        """Block until a version newer than this cursor's last read; ack it
        so the writer may proceed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 5e-5
        while True:
            version, length, _n, _acks = self._hdr()
            if version > self._last_version and version % 2 == 0:
                payload = bytes(self._mm[_HDR.size:_HDR.size + length])
                v2, = struct.unpack_from("<Q", self._mm, 0)
                if v2 == version:  # seqlock: no concurrent write observed
                    self._last_version = version
                    struct.pack_into("<Q", self._mm,
                                     24 + 8 * self.reader_idx, version)
                    if payload == _CLOSE:
                        raise ChannelClosedError(self.path)
                    return pickle.loads(payload)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel read timed out ({self.path})")
            time.sleep(delay)
            delay = min(delay * 2, 1e-3)

    # -- lifecycle --

    def close(self):
        try:
            self._mm.close()
        except BufferError:
            pass

    def unlink(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __reduce__(self):
        return (Channel, (self.path, self.capacity, False, 1,
                          self.reader_idx))
