"""Internal KV API over the head's control plane.

Parity: reference `python/ray/experimental/internal_kv.py`
(`_internal_kv_get/put/del/exists/list` over the GCS KV,
`gcs_kv_manager.h`). Works from the driver (direct) and from any worker
(request RPC to the head).
"""

from __future__ import annotations


def _rt():
    from ray_tpu.core.runtime import get_runtime
    return get_runtime()


def _is_head(rt) -> bool:
    from ray_tpu.core.runtime import Runtime
    return isinstance(rt, Runtime)


def _internal_kv_initialized() -> bool:
    try:
        _rt()
        return True
    except Exception:  # noqa: BLE001
        return False


def _internal_kv_put(key, value, overwrite: bool = True) -> bool:
    """Returns True if the key already existed. overwrite=False is atomic
    (single head-side check-and-set, like the reference's GCS KV PUT) —
    concurrent writers cannot both win."""
    rt = _rt()
    if _is_head(rt):
        with rt.lock:
            existed = key in rt.kv
            if overwrite or not existed:
                rt.kv[key] = value
        return existed
    if overwrite:
        return rt.request("kv_put", (key, value))
    return rt.request("kv_putnx", (key, value))


def _internal_kv_get(key):
    rt = _rt()
    if _is_head(rt):
        with rt.lock:
            return rt.kv.get(key)
    return rt.request("kv_get", key)


def _internal_kv_exists(key) -> bool:
    return _internal_kv_get(key) is not None


def _internal_kv_del(key):
    rt = _rt()
    if _is_head(rt):
        with rt.lock:
            rt.kv.pop(key, None)
    else:
        rt.request("kv_del", key)


def _internal_kv_take(key):
    """Atomic get+delete; returns None if absent (exactly one of N
    concurrent callers receives a present value)."""
    rt = _rt()
    if _is_head(rt):
        return rt.kv_take(key)
    return rt.request("kv_take", key)


def _internal_kv_list(prefix=b"") -> list:
    rt = _rt()
    if _is_head(rt):
        return rt.kv_keys(prefix)
    return rt.request("kv_keys", prefix)
