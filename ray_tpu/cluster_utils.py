"""In-process multi-node test cluster.

Parity: reference `python/ray/cluster_utils.py` `Cluster:135`/`add_node:202`
— the linchpin of distributed testing without hardware (SURVEY §4.3): N node
agents run as separate OS processes on one machine, each with its own
shared-memory store and worker pool, all believing they are distinct nodes.
The driver runs on the head node.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


class NodeHandle:
    def __init__(self, proc: subprocess.Popen, node_id_hex: str | None = None):
        self.proc = proc
        self.node_id = node_id_hex  # filled once registration is observed

    def kill(self):
        try:
            self.proc.kill()
        except ProcessLookupError:
            pass


class Cluster:
    """Start a head runtime plus N emulated nodes on this machine."""

    def __init__(self, initialize_head: bool = True,
                 head_node_args: dict | None = None):
        import ray_tpu
        from ray_tpu.core.runtime import get_runtime
        if initialize_head:
            ray_tpu.init(**(head_node_args or {}))
        self.rt = get_runtime()
        self.address = self.rt.enable_cluster()
        self.nodes: list[NodeHandle] = []

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 resources: dict | None = None,
                 object_store_memory: int | None = None,
                 wait: bool = True, timeout: float = 60.0) -> NodeHandle:
        import uuid
        node_id = uuid.uuid4().hex[:16]  # assigned here: exact attribution
        env = dict(os.environ)
        env.update(self.rt.config.to_env())
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        cmd = [sys.executable, "-m", "ray_tpu.core.node_agent",
               "--head", self.address,
               "--num-cpus", str(num_cpus),
               "--num-tpus", str(num_tpus),
               "--resources", json.dumps(resources or {}),
               "--node-id", node_id]
        if object_store_memory:
            cmd += ["--object-store-memory", str(object_store_memory)]
        with open(os.path.join(self.rt.session_dir, "logs",
                               f"node-agent-{len(self.nodes)}.out"),
                  "ab") as log:
            proc = subprocess.Popen(cmd, env=env, stdout=log,
                                    stderr=subprocess.STDOUT)
        handle = NodeHandle(proc, node_id)
        self.nodes.append(handle)
        if wait:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if any(n["node_id"] == node_id and n["alive"]
                       for n in self.rt.nodes_table()):
                    return handle
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"node agent exited with {proc.returncode} before "
                        f"registering")
                time.sleep(0.02)
            raise TimeoutError("node agent did not register in time")
        return handle

    def remove_node(self, node: NodeHandle, allow_graceful: bool = False):
        node.kill()
        try:
            node.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            pass
        self.nodes = [n for n in self.nodes if n is not node]
        # Head notices the TCP EOF immediately; wait for the table to agree.
        if node.node_id is not None:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                alive = {n["node_id"] for n in self.rt.nodes_table()
                         if n["alive"]}
                if node.node_id not in alive:
                    return
                time.sleep(0.02)

    def wait_for_nodes(self, n: int, timeout: float = 60.0):
        """Block until the cluster has n alive nodes (head included)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = sum(1 for x in self.rt.nodes_table() if x["alive"])
            if alive >= n:
                return
            time.sleep(0.02)
        raise TimeoutError(f"cluster never reached {n} nodes")

    def shutdown(self):
        import ray_tpu
        # Head shutdown first: it sends shutdown_node to live agents, which
        # tear down their stores/workers cleanly; SIGKILL is the fallback.
        ray_tpu.shutdown()
        for node in list(self.nodes):
            try:
                node.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                node.kill()
                try:
                    node.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
        self.nodes.clear()
