"""Flash attention: Pallas TPU kernels (forward + backward) with online
softmax.

The hot op of the model family (SURVEY §2.4 / pallas_guide.md). Tiled for the
MXU: grid = (batch*heads, q_blocks, k_blocks), fp32 accumulators in VMEM
scratch that persist across the innermost grid dimension, causal blocks
predicated with @pl.when so fully-masked tiles cost nothing. Falls back to a
jnp reference off-TPU (tests run the kernels in interpret mode to check the
exact same code path).

Backward: flash-style recompute in two Pallas kernels (dq; dkv), bf16 matmul
inputs with fp32 MXU accumulation. The forward saves the per-row logsumexp
(replicated along a 128-lane minor dim so both backward kernels read it in
their natural layout without in-kernel relayouts). A jnp recompute backward
(`impl="reference"`) remains as the numerics oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax 0.4.x names it TPUCompilerParams; same fields.
    pltpu.CompilerParams = pltpu.TPUCompilerParams

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, scale: float, causal: bool,
                bq: int, bk: int, nk: int, with_lse: bool,
                kv_len: int | None):
    if with_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_scr, l_scr, acc_scr = rest
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # For causal attention, blocks strictly above the diagonal contribute
    # nothing; predicate them out entirely.
    run = True if not causal else (ki * bk <= qi * bq + bq - 1)

    @pl.when(run)
    def _compute():
        # bf16 straight into the MXU; fp32 comes out via preferred_element_type.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk] f32
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + qi * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ki * bk
            s = jnp.where(rows >= cols, s, NEG_INF)
        if kv_len is not None:
            # Sequence padded to the block multiple: hide the padded keys
            # (padded QUERY rows produce garbage and are sliced off by the
            # caller; under causal masking the padded keys sit above every
            # real row's diagonal already, but non-causal needs this).
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ki * bk
            s = jnp.where(cols < kv_len, s, NEG_INF)
        m_prev = m_scr[:, :1]                                  # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)             # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                                 # [bq, bk] f32
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        if with_lse:
            # logsumexp per row, replicated along the 128-lane minor dim so
            # the backward kernels read it without relayouts.
            lse_ref[0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l),
                                          lse_ref.shape[1:])


def _flash_fwd(q, k, v, scale, causal, bq, bk, interpret, with_lse=True,
               kv_len=None):
    """q,k,v: [BH, S, D] -> (out [BH, S, D], lse [BH, S, 128] f32) when
    with_lse, else out alone (primal-only path: a pallas_call output cannot
    be DCE'd, so the inference path must not emit the lse at all)."""
    bh, s, d = q.shape
    bq = min(bq, s)
    bk = min(bk, s)
    nq = pl.cdiv(s, bq)
    nk = pl.cdiv(s, bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk, with_lse=with_lse,
                               kv_len=kv_len)
    out_shape = jax.ShapeDtypeStruct((bh, s, d), q.dtype)
    out_spec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    if with_lse:
        out_shape = (out_shape,
                     jax.ShapeDtypeStruct((bh, s, 128), jnp.float32))
        out_spec = (out_spec,
                    pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)))
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_spec,
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max
            pltpu.VMEM((bq, 128), jnp.float32),   # running sum
            pltpu.VMEM((bq, d), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * s * s * d // (2 if causal else 1),
            bytes_accessed=3 * bh * s * d * q.dtype.itemsize,
            transcendentals=bh * s * s),
    )(q, k, v)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale: float, causal: bool, bq: int, bk: int,
                   nk: int, kv_len: int | None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True if not causal else (ki * bk <= qi * bq + bq - 1)

    @pl.when(run)
    def _compute():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + qi * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ki * bk
            s = jnp.where(rows >= cols, s, NEG_INF)
        if kv_len is not None:
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ki * bk
            s = jnp.where(cols < kv_len, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])                     # [bq, bk]
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bq, bk]
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bq, d]

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                    causal: bool, bq: int, bk: int, nq: int,
                    kv_len: int | None):
    ki = pl.program_id(1)
    qj = pl.program_id(2)

    @pl.when(qj == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # q blocks entirely before this k block contribute nothing under causal.
    run = True if not causal else (qj * bq + bq - 1 >= ki * bk)

    @pl.when(run)
    def _compute():
        # Work in the transposed orientation [bk, bq]: the per-q-row lse and
        # delta then broadcast along sublanes, which is free on TPU.
        st = jax.lax.dot_general(
            k_ref[0], q_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale        # [bk, bq]
        if causal:
            krows = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0) + ki * bk
            qcols = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 1) + qj * bq
            st = jnp.where(qcols >= krows, st, NEG_INF)
        if kv_len is not None:
            krows = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0) + ki * bk
            st = jnp.where(krows < kv_len, st, NEG_INF)
        pt = jnp.exp(st - lse_ref[0][:1])                      # [bk, bq]
        dpt = jax.lax.dot_general(
            v_ref[0], do_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bk, bq]
        dst = pt * (dpt - delta_ref[0][:1]) * scale
        dv_scr[:] += jax.lax.dot_general(
            pt.astype(do_ref.dtype), do_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bk, d]
        dk_scr[:] += jax.lax.dot_general(
            dst.astype(q_ref.dtype), q_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bk, d]

    @pl.when(qj == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, scale, causal, bq, bk, interpret,
               kv_len=None):
    """Backward via flash-style recompute. lse: flat [BH, S] from forward."""
    bh, s, d = q.shape
    bq = min(bq, s)
    bk = min(bk, s)
    nq = pl.cdiv(s, bq)
    nk = pl.cdiv(s, bk)
    # delta_i = rowsum(dO_i * O_i). Both lse and delta are fed to the dq
    # kernel lane-replicated [BH, S, 128] and to the dkv kernel transposed
    # [BH, 8, S] (seq along lanes) — each kernel reads its natural layout.
    delta_flat = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                         axis=-1)                              # [BH, S]
    delta = jnp.broadcast_to(delta_flat[..., None], (bh, s, 128))
    lse_rep = jnp.broadcast_to(lse[..., None], (bh, s, 128))
    lse_t = jnp.broadcast_to(lse[:, None, :], (bh, 8, s))
    delta_t = jnp.broadcast_to(delta_flat[:, None, :], (bh, 8, s))
    g = g.astype(q.dtype)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, kv_len=kv_len),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),    # q
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),    # k
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),    # v
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),    # do
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)),  # lse
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)),  # delta
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=6 * bh * s * s * d // (2 if causal else 1),
            bytes_accessed=4 * bh * s * d * q.dtype.itemsize,
            transcendentals=bh * s * s),
    )(q, k, v, g, lse_rep, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, kv_len=kv_len),
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v.dtype)),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0)),    # k
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0)),    # v
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, j, 0)),    # q
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, j, 0)),    # do
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, j)),    # lse_t
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, j)),    # delta_t
        ],
        out_specs=(pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0))),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=6 * bh * s * s * d // (2 if causal else 1),
            bytes_accessed=4 * bh * s * d * q.dtype.itemsize,
            transcendentals=bh * s * s),
    )(k, v, q, g, lse_t, delta_t)
    return dq, dk, dv


def _reference(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


# 1024-row tiles: ~25-30% faster than 512 at S in [1k, 4k] on v5e (fewer
# grid cells, better MXU occupancy per cell) and still inside the 16MB
# scoped-vmem budget at D=64..128; 2048 blows scoped vmem. Shorter or
# misaligned sequences shrink via min/gcd below.
_BQ = 1024
_BK = 1024


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, impl, kv_len=None, blk=_BQ):
    if impl == "reference":
        return _reference(q, k, v, scale, causal)
    return _flash_fwd(q, k, v, scale, causal, bq=blk, bk=blk,
                      interpret=(impl == "interpret"), with_lse=False,
                      kv_len=kv_len)


def _flash_vjp_fwd(q, k, v, scale, causal, impl, kv_len=None, blk=_BQ):
    if impl == "reference":
        return _reference(q, k, v, scale, causal), (q, k, v, None, None)
    out, lse = _flash_fwd(q, k, v, scale, causal, bq=blk, bk=blk,
                          interpret=(impl == "interpret"), kv_len=kv_len)
    # Save the flat [BH, S] logsumexp — the lane-replicated form would
    # multiply the per-layer residual footprint by 128.
    return out, (q, k, v, out, lse[:, :, 0])


def _flash_vjp_bwd(scale, causal, impl, kv_len, blk, res, g):
    q, k, v, o, lse = res
    if impl == "reference":
        # jnp recompute backward — the numerics oracle.
        _, vjp = jax.vjp(lambda q, k, v: _reference(q, k, v, scale, causal),
                         q, k, v)
        return vjp(g)
    return _flash_bwd(q, k, v, o, lse, g, scale, causal, bq=blk, bk=blk,
                      interpret=(impl == "interpret"), kv_len=kv_len)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = True, scale: float | None = None,
                    impl: str = "auto"):
    """q: [B, S, H, D], k/v: [B, S, Hkv, D] (GQA broadcast inside).

    impl: "auto" (pallas on TPU, reference elsewhere), "pallas",
    "interpret" (pallas interpreter — used by CPU tests), "reference".
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    if hkv != h:
        rep = h // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    if impl == "auto":
        impl = "pallas" if _on_tpu() else "reference"
    kv_len = None
    s_pad = s
    blk = _BQ
    if impl in ("pallas", "interpret"):
        # The kernels assume the sequence tiles exactly into the block size
        # (partial pallas blocks carry undefined values that the dkv
        # accumulation would fold into valid rows). Pad to the next
        # 128-lane multiple and mask the padded keys statically via kv_len
        # instead of falling back to the O(S^2)-memory dense reference —
        # at the lengths the kernel exists for, the fallback OOMs. The
        # tile shrinks to whatever still divides the padded length (at
        # most one 128-row tile of overhead, not a 512-multiple round-up).
        import math as _math
        blk = min(_BQ, s)
        if s % blk or blk % 8:  # untileable or sublane-misaligned
            s_pad = max(128, -(-s // 128) * 128)
            blk = _math.gcd(s_pad, _BQ)
            pad = [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]
            q = jnp.pad(q, pad)
            k = jnp.pad(k, pad)
            v = jnp.pad(v, pad)
            kv_len = s
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s_pad, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, s_pad, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, s_pad, d)
    out = _flash(qt, kt, vt, scale, causal, impl, kv_len, blk)
    out = out.reshape(b, h, s_pad, d).transpose(0, 2, 1, 3)
    return out[:, :s] if s_pad != s else out
