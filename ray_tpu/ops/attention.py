"""Flash attention: Pallas TPU kernel with online softmax.

The hot op of the model family (SURVEY §2.4 / pallas_guide.md). Tiled for the
MXU: grid = (batch*heads, q_blocks, k_blocks), fp32 accumulators in VMEM
scratch that persist across the innermost k dimension, causal blocks
predicated with @pl.when so fully-masked tiles cost nothing. Falls back to a
jnp reference off-TPU (tests run the kernel in interpret mode to check the
exact same code path).

Backward: custom_vjp with recompute (flash-style) expressed in jnp — XLA
fuses it well; a Pallas backward kernel is a later optimization.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                scale: float, causal: bool, bq: int, bk: int, nk: int):
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # For causal attention, blocks strictly above the diagonal contribute
    # nothing; predicate them out entirely.
    run = True if not causal else (ki * bk <= qi * bq + bq - 1)

    @pl.when(run)
    def _compute():
        # bf16 straight into the MXU; fp32 comes out via preferred_element_type.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk] f32
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + qi * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ki * bk
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_scr[:, :1]                                  # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)             # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                                 # [bq, bk] f32
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] /
                    jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal, bq, bk, interpret):
    """q,k,v: [BH, S, D] -> out [BH, S, D]."""
    bh, s, d = q.shape
    bq = min(bq, s)
    bk = min(bk, s)
    nq = pl.cdiv(s, bq)
    nk = pl.cdiv(s, bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max
            pltpu.VMEM((bq, 128), jnp.float32),   # running sum
            pltpu.VMEM((bq, d), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * s * s * d // (2 if causal else 1),
            bytes_accessed=3 * bh * s * d * q.dtype.itemsize,
            transcendentals=bh * s * s),
    )(q, k, v)


def _reference(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, scale, causal, impl):
    return _flash_dispatch(q, k, v, scale, causal, impl)


def _flash_dispatch(q, k, v, scale, causal, impl):
    if impl == "reference":
        return _reference(q, k, v, scale, causal)
    return _flash_fwd(q, k, v, scale, causal, bq=512, bk=512,
                      interpret=(impl == "interpret"))


def _flash_vjp_fwd(q, k, v, scale, causal, impl):
    return _flash_dispatch(q, k, v, scale, causal, impl), (q, k, v)


def _flash_vjp_bwd(scale, causal, impl, res, g):
    q, k, v = res
    # Recompute-based backward in jnp; correct and XLA-fused.
    _, vjp = jax.vjp(lambda q, k, v: _reference(q, k, v, scale, causal),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = True, scale: float | None = None,
                    impl: str = "auto"):
    """q: [B, S, H, D], k/v: [B, S, Hkv, D] (GQA broadcast inside).

    impl: "auto" (pallas on TPU, reference elsewhere), "pallas",
    "interpret" (pallas interpreter — used by CPU tests), "reference".
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    if hkv != h:
        rep = h // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    if impl == "auto":
        impl = "pallas" if _on_tpu() else "reference"
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    out = _flash(qt, kt, vt, scale, causal, impl)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
