"""Paged-KV decode attention kernel (TPU Pallas).

The decode hot loop attends one query token per slot against that slot's
paged KV history. XLA lowers the naive formulation (gather pages into a
contiguous [B, T] cache, then attend) at ~10% of HBM bandwidth — the page
gather dominated the whole decode step. This kernel instead walks each
slot's page table and DMAs exactly the pages it owns through a two-deep
manual pipeline, flash-accumulating on the fly, so per-step traffic is
the true KV working set.

Parity: the role of vLLM's paged attention CUDA kernel inside the
reference's LLM serving stack (`python/ray/llm/_internal/serve/deployments/
llm/vllm/`); the TPU shape follows the public JetStream/MaxText paged
decode pattern (scalar-prefetched page tables + manual double-buffered
page DMA).

Layouts:
  q            [B, n_heads, head_dim]
  k_pages, v_pages [n_kv_heads, num_pages, head_dim, page_size]
      (head_dim BEFORE page: a page's DMA slice then has trailing dims
      (head_dim, page) = (64|128, 128), which Mosaic can tile — with page
      last-minor the 64-wide head_dim would land on the 128-lane axis and
      the per-page slice fails to lower)
  lengths      [B]  number of valid tokens (attend positions < lengths)
  page_tables  [B, P]  page ids in position order (entry 0 = scratch page)

Returns [B, n_heads, head_dim].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax 0.4.x names it TPUCompilerParams; same fields.
    pltpu.CompilerParams = pltpu.TPUCompilerParams

_NEG = -0.7 * float(np.finfo(np.float32).max)


def paged_decode_attention(q, k_pages, v_pages, lengths, page_tables, *,
                           interpret: bool | None = None):
    """Flash decode over paged KV; see module docstring for layouts.

    interpret=None auto-selects: the Mosaic lowering needs a real TPU
    backend; everywhere else (CPU tests, multichip dryrun) the kernel
    runs in interpret mode. RAY_TPU_PAGED_ATTN_IMPL=xla forces the plain
    XLA gather-attend formulation — the fallback path the tp>1 virtual-
    mesh dryrun uses (GSPMD shards it like any einsum; Pallas interpret
    mode is also ~100x slower than XLA on CPU)."""
    import os
    if os.environ.get("RAY_TPU_PAGED_ATTN_IMPL") == "xla":
        return _paged_decode_xla(q, k_pages, v_pages, lengths, page_tables)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    page, hd = k_pages.shape[3], k_pages.shape[2]
    if not interpret and (page % 128 or hd % 8):
        # Mosaic can only DMA page slices whose trailing dims tile to
        # (8, 128); off-size pages (toy/test configs) fall back to the
        # XLA gather-attend formulation — slower, always correct.
        return _paged_decode_xla(q, k_pages, v_pages, lengths, page_tables)
    return _paged_decode_dma(q, k_pages, v_pages, lengths,
                             page_tables, interpret=interpret)


@jax.jit
def _paged_decode_xla(q, k_pages, v_pages, lengths, page_tables):
    return paged_decode_attention_reference(q, k_pages, v_pages, lengths,
                                            page_tables)


def _dma_kernel(lengths_ref, tables_ref,  # scalar prefetch (SMEM)
                q_ref, k_hbm, v_hbm, o_ref,
                kbuf, vbuf, m_ref, l_ref, acc_ref, sem, *, page: int,
                scale: float, pages_per_seq: int, n_q: int = 1):
    """One grid step per slot; the slot's pages stream HBM->VMEM through
    a two-deep manual DMA pipeline (page i+1 in flight while page i is in
    the flash update). One grid step per slot keeps grid overhead off the
    hot path — a BlockSpec-per-page variant spends more time stepping the
    grid than computing (measured ~0.8ms per layer call vs ~0.2ms for
    this shape).

    n_q > 1 (speculative verify): the q block carries n_q query tokens per
    slot folded into the head-group axis with the query index MINOR
    ([hkv, g*n_q, hd], layout [g, n_q]); query j sits at absolute position
    lengths-1+j, so its causal limit is lengths+j. The flash accumulators
    simply widen by n_q rows."""
    b = pl.program_id(0)
    length = lengths_ref[b]
    npg = jnp.minimum(
        jax.lax.div(length + (n_q - 1) + page - 1, page), pages_per_seq)

    def start_copy(i, slot):
        pid = tables_ref[b, i]
        pltpu.make_async_copy(
            k_hbm.at[:, pid], kbuf.at[slot], sem.at[slot, 0]).start()
        pltpu.make_async_copy(
            v_hbm.at[:, pid], vbuf.at[slot], sem.at[slot, 1]).start()

    def wait_copy(slot):
        pltpu.make_async_copy(
            k_hbm.at[:, 0], kbuf.at[slot], sem.at[slot, 0]).wait()
        pltpu.make_async_copy(
            v_hbm.at[:, 0], vbuf.at[slot], sem.at[slot, 1]).wait()

    m_ref[...] = jnp.full_like(m_ref, _NEG)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(npg > 0)
    def _first():
        start_copy(0, 0)

    q = q_ref[0].astype(jnp.float32)                   # [hkv, g, hd]
    hkv, g, hd = q.shape

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < npg)
        def _prefetch():
            start_copy(i + 1, 1 - slot)

        wait_copy(slot)
        k = kbuf[slot].astype(jnp.float32)             # [hkv, hd, page]
        v = vbuf[slot].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [hkv, g, page]
        pos = i * page + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=2)
        if n_q == 1:
            limit = length
        else:
            # row r of the folded axis is query j = r % n_q
            limit = length + jax.lax.rem(
                jax.lax.broadcasted_iota(jnp.int32, s.shape, dimension=1),
                n_q)
        s = jnp.where(pos < limit, s, _NEG)
        m_old = m_ref[...]                             # [hkv*g, 128]
        s2 = s.reshape(hkv * g, page)
        m_cur = jnp.max(s2, axis=1, keepdims=True)
        m_new = jnp.maximum(m_old, jnp.broadcast_to(m_cur, m_old.shape))
        alpha = jnp.exp(m_old[:, :1] - m_new[:, :1])
        p_exp = jnp.exp(s2 - m_new[:, :1])
        l_ref[...] = l_ref[...] * alpha + jnp.sum(
            p_exp, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p_exp.reshape(hkv, g, page), v,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [hkv, g, hd]
        acc_ref[...] = acc_ref[...] * alpha[:, None].reshape(
            hkv, g, 1) + pv
        m_ref[...] = m_new
        return 0

    jax.lax.fori_loop(0, npg, body, 0)
    l = l_ref[...][:, :1]
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc_ref[...] / l.reshape(hkv, g, 1)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_decode_dma(q, k_pages, v_pages, lengths, page_tables, *,
                      interpret: bool = False):
    B, h, hd = q.shape
    hkv, N, _, page = k_pages.shape
    assert h % hkv == 0, (h, hkv)
    g = h // hkv
    P = page_tables.shape[1]
    q4 = q.reshape(B, hkv, g, hd)
    scale = 1.0 / float(np.sqrt(hd))
    kernel = functools.partial(_dma_kernel, page=page, scale=scale,
                               pages_per_seq=P)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, hkv, g, hd),
                             lambda b, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),   # k_pages in HBM
                pl.BlockSpec(memory_space=pl.ANY),   # v_pages in HBM
            ],
            out_specs=pl.BlockSpec((1, hkv, g, hd),
                                   lambda b, lens, tbl: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, hkv, hd, page), k_pages.dtype),  # kbuf
                pltpu.VMEM((2, hkv, hd, page), v_pages.dtype),  # vbuf
                pltpu.VMEM((hkv * g, 128), jnp.float32),        # m
                pltpu.VMEM((hkv * g, 128), jnp.float32),        # l
                pltpu.VMEM((hkv, g, hd), jnp.float32),          # acc
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, hkv, g, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(lengths, page_tables, q4, k_pages, v_pages)
    return out.reshape(B, h, hd)


def _fused_kernel(lengths_ref, tables_ref,  # scalar prefetch (SMEM)
                  q_ref, knew_ref, vnew_ref, k_hbm, v_hbm,
                  o_ref, ko_ref, vo_ref,
                  kbuf, vbuf, m_ref, l_ref, acc_ref, sem, wsem, *,
                  page: int, scale: float, pages_per_seq: int, n_q: int,
                  layer: int):
    """Verify attention with the KV INSERT fused in (JetStream-style):
    the kernel already streams every page of the slot; when the page
    holding the n_q new tokens passes through VMEM, their K/V columns are
    merged in (one [hkv*hd, n_q] x [n_q, page] one-hot matmul) and the
    merged page is DMAd back to the pool, which is input/output-aliased.
    Token-granular XLA scatters serialized at ~2us/row and cost more than
    the whole forward; here the write rides the DMA pipeline the attend
    already pays for."""
    b = pl.program_id(0)
    length = lengths_ref[b]          # = base + 1 (limit of query 0)
    base = length - 1                # position of the first new token
    npg = jnp.minimum(
        jax.lax.div(length + (n_q - 1) + page - 1, page), pages_per_seq)

    def start_copy(i, slot):
        pid = tables_ref[b, i]
        pltpu.make_async_copy(
            k_hbm.at[layer, :, pid], kbuf.at[slot], sem.at[slot, 0]).start()
        pltpu.make_async_copy(
            v_hbm.at[layer, :, pid], vbuf.at[slot], sem.at[slot, 1]).start()

    def wait_copy(slot):
        pltpu.make_async_copy(
            k_hbm.at[layer, :, 0], kbuf.at[slot], sem.at[slot, 0]).wait()
        pltpu.make_async_copy(
            v_hbm.at[layer, :, 0], vbuf.at[slot], sem.at[slot, 1]).wait()

    m_ref[...] = jnp.full_like(m_ref, _NEG)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(npg > 0)
    def _first():
        start_copy(0, 0)

    q = q_ref[0].astype(jnp.float32)               # [hkv, g*n_q, hd]
    hkv, gq, hd = q.shape
    # page-padded new-token blocks in NATIVE dtype (bitwise-exact writes)
    knew = knew_ref[0]                             # [hkv*hd, n_q]
    vnew = vnew_ref[0]
    zpad = jnp.zeros((knew.shape[0], page - n_q), knew.dtype)
    knew_pad = jnp.concatenate([knew, zpad], axis=1)
    vnew_pad = jnp.concatenate([vnew, zpad.astype(vnew.dtype)], axis=1)

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < npg)
        def _prefetch():
            start_copy(i + 1, 1 - slot)

        wait_copy(slot)

        # ---- fused insert: this page holds new-token positions? ----
        lo, hi = i * page, (i + 1) * page
        overlaps = (lo <= base + n_q - 1) & (hi > base)

        @pl.when(overlaps)
        def _merge():
            pid = tables_ref[b, i]
            # Token j lands at column base+j-lo. Shift the (page-padded)
            # new-token block so column p holds token p-(base-lo), then
            # select the covered columns. Roll+select keeps the written
            # values BITWISE exact — a one-hot matmul merge would round
            # through the MXU's bf16 multiply and break the speculative
            # greedy-exactness contract.
            cols = jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
            idx = cols - (base - lo)
            sel = (idx >= 0) & (idx < n_q)             # [1, page]
            shift = jax.lax.rem(base - lo + page, page)
            # roll only lowers for 32-bit lanes; bf16 -> f32 -> bf16 is
            # exact (f32 is a superset), so the write stays bitwise
            newk = pltpu.roll(knew_pad.astype(jnp.float32), shift,
                              1).reshape(hkv, hd, page)
            newv = pltpu.roll(vnew_pad.astype(jnp.float32), shift,
                              1).reshape(hkv, hd, page)
            sel = sel.reshape(1, 1, page)
            kbuf[slot] = jnp.where(sel, newk.astype(kbuf.dtype),
                                   kbuf[slot])
            vbuf[slot] = jnp.where(sel, newv.astype(vbuf.dtype),
                                   vbuf[slot])
            # write the merged page back to the (aliased) pool
            pltpu.make_async_copy(
                kbuf.at[slot], k_hbm.at[layer, :, pid], wsem.at[0]).start()
            pltpu.make_async_copy(
                vbuf.at[slot], v_hbm.at[layer, :, pid], wsem.at[1]).start()
            pltpu.make_async_copy(
                kbuf.at[slot], k_hbm.at[layer, :, pid], wsem.at[0]).wait()
            pltpu.make_async_copy(
                vbuf.at[slot], v_hbm.at[layer, :, pid], wsem.at[1]).wait()

        k = kbuf[slot].astype(jnp.float32)             # [hkv, hd, page]
        v = vbuf[slot].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # [hkv, gq, page]
        pos = i * page + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=2)
        limit = length + jax.lax.rem(
            jax.lax.broadcasted_iota(jnp.int32, s.shape, dimension=1),
            n_q)
        s = jnp.where(pos < limit, s, _NEG)
        m_old = m_ref[...]
        s2 = s.reshape(hkv * gq, page)
        m_cur = jnp.max(s2, axis=1, keepdims=True)
        m_new = jnp.maximum(m_old, jnp.broadcast_to(m_cur, m_old.shape))
        alpha = jnp.exp(m_old[:, :1] - m_new[:, :1])
        p_exp = jnp.exp(s2 - m_new[:, :1])
        l_ref[...] = l_ref[...] * alpha + jnp.sum(
            p_exp, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p_exp.reshape(hkv, gq, page), v,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha[:, None].reshape(
            hkv, gq, 1) + pv
        m_ref[...] = m_new
        return 0

    jax.lax.fori_loop(0, npg, body, 0)
    l = l_ref[...][:, :1]
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc_ref[...] / l.reshape(hkv, gq, 1)).astype(o_ref.dtype)


def paged_verify_insert_attention(q, pool_k, pool_v, knew, vnew,
                                  lengths, page_tables, layer: int, *,
                                  interpret: bool | None = None):
    """Fused insert+attend for the speculative verify step, against ONE
    layer of the stacked pools.

    q [B, S, h, hd]; knew/vnew [B, S, hkv, hd] are the S new tokens'
    K/V, written into pool[layer] at positions lengths-1..lengths-1+S-1
    as a side effect (the pools are input/output-aliased, so the caller
    gets the same buffers back — no copies); query j attends
    pos < lengths + j. Returns (attn [B, S, h, hd], pool_k, pool_v)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    page, hd = pool_k.shape[4], pool_k.shape[3]
    # Interpret mode does not propagate the kernel's in-place HBM
    # writebacks through the input/output aliasing (verified empirically:
    # the aliased outputs come back unmodified), so CPU paths — tests and
    # the multichip dryrun — take the XLA insert+attend fallback. The
    # Mosaic path also needs (8, 128)-tileable page slices.
    if interpret or page % 128 or hd % 8:
        return _verify_insert_xla(q, pool_k, pool_v, knew, vnew,
                                  lengths, page_tables, layer)
    return _verify_insert_dma(q, pool_k, pool_v, knew, vnew, lengths,
                              page_tables, layer=layer,
                              interpret=False)


@functools.partial(jax.jit, static_argnames=("layer",))
def _verify_insert_xla(q, pool_k, pool_v, knew, vnew, lengths,
                       page_tables, layer):
    pool_k, pool_v = _insert_tokens_xla(pool_k, pool_v, knew, vnew,
                                        lengths, page_tables, layer)
    out = paged_verify_attention_reference(q, pool_k[layer],
                                           pool_v[layer], lengths,
                                           page_tables)
    return out, pool_k, pool_v


def _insert_tokens_xla(pool_k, pool_v, knew, vnew, lengths,
                       page_tables, layer):
    """Token-scatter fallback insert (CPU tests / odd shapes)."""
    B, S = knew.shape[:2]
    hkv = pool_k.shape[1]
    page = pool_k.shape[4]
    P = page_tables.shape[1]
    positions = (lengths - 1)[:, None] + jnp.arange(S)[None]
    w_idx = jnp.clip(positions // page, 0, P - 1)
    w_page = jnp.take_along_axis(page_tables, w_idx, 1)
    w_page = jnp.where(positions // page >= P, 0, w_page)
    w_off = positions % page
    hkv_idx = jnp.arange(hkv)[:, None, None]
    pool_k = pool_k.at[layer, hkv_idx, w_page[None], :, w_off[None]].set(
        knew.transpose(2, 0, 1, 3).astype(pool_k.dtype))
    pool_v = pool_v.at[layer, hkv_idx, w_page[None], :, w_off[None]].set(
        vnew.transpose(2, 0, 1, 3).astype(pool_v.dtype))
    return pool_k, pool_v


@functools.partial(jax.jit, static_argnames=("interpret", "layer"),
                   donate_argnums=(1, 2))
def _verify_insert_dma(q, k_pages, v_pages, knew, vnew, lengths,
                       page_tables, *, layer: int = 0,
                       interpret: bool = False):
    B, S, h, hd = q.shape
    L, hkv, N, _, page = k_pages.shape
    assert h % hkv == 0, (h, hkv)
    g = h // hkv
    P = page_tables.shape[1]
    q4 = q.reshape(B, S, hkv, g, hd).transpose(0, 2, 3, 1, 4).reshape(
        B, hkv, g * S, hd)
    # [B, S, hkv, hd] -> [B, hkv*hd, S] for the in-kernel one-hot matmul
    kn = knew.transpose(0, 2, 3, 1).reshape(B, hkv * hd, S)
    vn = vnew.transpose(0, 2, 3, 1).reshape(B, hkv * hd, S)
    scale = 1.0 / float(np.sqrt(hd))
    kernel = functools.partial(_fused_kernel, page=page, scale=scale,
                               pages_per_seq=P, n_q=S, layer=layer)
    out, k_pages, v_pages = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, hkv, g * S, hd),
                             lambda b, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec((1, hkv * hd, S),
                             lambda b, lens, tbl: (b, 0, 0)),
                pl.BlockSpec((1, hkv * hd, S),
                             lambda b, lens, tbl: (b, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),      # k_pages in HBM
                pl.BlockSpec(memory_space=pl.ANY),      # v_pages in HBM
            ],
            out_specs=[
                pl.BlockSpec((1, hkv, g * S, hd),
                             lambda b, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),      # aliased k_pages
                pl.BlockSpec(memory_space=pl.ANY),      # aliased v_pages
            ],
            scratch_shapes=[
                pltpu.VMEM((2, hkv, hd, page), k_pages.dtype),  # kbuf
                pltpu.VMEM((2, hkv, hd, page), v_pages.dtype),  # vbuf
                pltpu.VMEM((hkv * g * S, 128), jnp.float32),    # m
                pltpu.VMEM((hkv * g * S, 128), jnp.float32),    # l
                pltpu.VMEM((hkv, g * S, hd), jnp.float32),      # acc
                pltpu.SemaphoreType.DMA((2, 2)),
                pltpu.SemaphoreType.DMA((2,)),                  # writeback
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, hkv, g * S, hd), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # operand indices count the scalar-prefetch args first:
        # 0=lengths 1=tables 2=q 3=knew 4=vnew 5=k_pages 6=v_pages
        input_output_aliases={5: 1, 6: 2},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(lengths, page_tables, q4, kn, vn, k_pages, v_pages)
    out = out.reshape(B, hkv, g, S, hd).transpose(0, 3, 1, 2, 4).reshape(
        B, S, h, hd)
    return out, k_pages, v_pages


def paged_verify_attention(q, k_pages, v_pages, lengths, page_tables, *,
                           interpret: bool | None = None):
    """Multi-query paged attention for speculative verify: q [B, S, h, hd]
    holds S query tokens per slot at consecutive positions, whose KV is
    already written to the pool; query j attends pos < lengths + j
    (`lengths` = the causal limit of query 0, i.e. its position + 1).
    Returns [B, S, h, hd]. Same DMA pipeline as decode — the S queries
    fold into the head-group axis, so verifying K drafts costs ONE pass
    over the slot's pages instead of K+1."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    page, hd = k_pages.shape[3], k_pages.shape[2]
    if not interpret and (page % 128 or hd % 8):
        return _paged_verify_xla(q, k_pages, v_pages, lengths, page_tables)
    return _paged_verify_dma(q, k_pages, v_pages, lengths, page_tables,
                             interpret=interpret)


@jax.jit
def _paged_verify_xla(q, k_pages, v_pages, lengths, page_tables):
    return paged_verify_attention_reference(q, k_pages, v_pages, lengths,
                                            page_tables)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_verify_dma(q, k_pages, v_pages, lengths, page_tables, *,
                      interpret: bool = False):
    B, S, h, hd = q.shape
    hkv, N, _, page = k_pages.shape
    assert h % hkv == 0, (h, hkv)
    g = h // hkv
    P = page_tables.shape[1]
    # fold queries into the group axis, query index MINOR: [g, S]
    q4 = q.reshape(B, S, hkv, g, hd).transpose(0, 2, 3, 1, 4).reshape(
        B, hkv, g * S, hd)
    scale = 1.0 / float(np.sqrt(hd))
    kernel = functools.partial(_dma_kernel, page=page, scale=scale,
                               pages_per_seq=P, n_q=S)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, hkv, g * S, hd),
                             lambda b, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),      # k_pages in HBM
                pl.BlockSpec(memory_space=pl.ANY),      # v_pages in HBM
            ],
            out_specs=pl.BlockSpec((1, hkv, g * S, hd),
                                   lambda b, lens, tbl: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, hkv, hd, page), k_pages.dtype),  # kbuf
                pltpu.VMEM((2, hkv, hd, page), v_pages.dtype),  # vbuf
                pltpu.VMEM((hkv * g * S, 128), jnp.float32),    # m
                pltpu.VMEM((hkv * g * S, 128), jnp.float32),    # l
                pltpu.VMEM((hkv, g * S, hd), jnp.float32),      # acc
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, hkv, g * S, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(lengths, page_tables, q4, k_pages, v_pages)
    return out.reshape(B, hkv, g, S, hd).transpose(0, 3, 1, 2, 4).reshape(
        B, S, h, hd)


def paged_verify_attention_reference(q, k_pages, v_pages, lengths,
                                     page_tables):
    """Dense reference for the verify path: gather pages, per-query causal
    mask (query j: pos < lengths + j), softmax."""
    B, S, h, hd = q.shape
    hkv, N, _, page = k_pages.shape
    g = h // hkv
    P = page_tables.shape[1]
    T = P * page
    ck = k_pages[:, page_tables]          # [hkv, B, P, hd, page]
    cv = v_pages[:, page_tables]
    ck = jnp.moveaxis(ck, 0, 1).transpose(0, 1, 2, 4, 3).reshape(
        B, hkv, T, hd)
    cv = jnp.moveaxis(cv, 0, 1).transpose(0, 1, 2, 4, 3).reshape(
        B, hkv, T, hd)
    q5 = q.reshape(B, S, hkv, g, hd).transpose(0, 2, 3, 1, 4).astype(
        jnp.float32)                      # [B, hkv, g, S, hd]
    s = jnp.einsum("bkgsd,bktd->bkgst", q5, ck.astype(jnp.float32))
    s = s / np.sqrt(hd)
    limit = lengths[:, None] + jnp.arange(S)[None]          # [B, S]
    mask = (jnp.arange(T)[None, None, None, None]
            < limit[:, None, None, :, None])
    s = jnp.where(mask, s, -jnp.inf)
    pr = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgst,bktd->bkgsd", pr, cv.astype(jnp.float32))
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S, h, hd).astype(
        q.dtype)


def paged_decode_attention_reference(q, k_pages, v_pages, lengths,
                                     page_tables):
    """Dense reference for tests: gather pages, mask, softmax."""
    B, h, hd = q.shape
    hkv, N, _, page = k_pages.shape
    g = h // hkv
    P = page_tables.shape[1]
    T = P * page
    ck = k_pages[:, page_tables]          # [hkv, B, P, hd, page]
    cv = v_pages[:, page_tables]
    # -> [B, hkv, T, hd]
    ck = jnp.moveaxis(ck, 0, 1).transpose(0, 1, 2, 4, 3).reshape(
        B, hkv, T, hd)
    cv = jnp.moveaxis(cv, 0, 1).transpose(0, 1, 2, 4, 3).reshape(
        B, hkv, T, hd)
    q4 = q.reshape(B, hkv, g, hd).astype(jnp.float32)
    s = jnp.einsum("bkgd,bktd->bkgt", q4, ck.astype(jnp.float32))
    s = s / np.sqrt(hd)
    mask = jnp.arange(T)[None, None, None] < lengths[:, None, None, None]
    s = jnp.where(mask, s, -jnp.inf)
    pr = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgt,bktd->bkgd", pr, cv.astype(jnp.float32))
    return out.reshape(B, h, hd).astype(q.dtype)
