"""Paged-KV decode attention kernel (TPU Pallas).

The decode hot loop attends one query token per slot against that slot's
paged KV history. XLA lowers the naive formulation (gather pages into a
contiguous [B, T] cache, then attend) at ~10% of HBM bandwidth — the page
gather dominated the whole decode step. This kernel instead walks each
slot's page table and DMAs exactly the pages it owns through a two-deep
manual pipeline, flash-accumulating on the fly, so per-step traffic is
the true KV working set.

Parity: the role of vLLM's paged attention CUDA kernel inside the
reference's LLM serving stack (`python/ray/llm/_internal/serve/deployments/
llm/vllm/`); the TPU shape follows the public JetStream/MaxText paged
decode pattern (scalar-prefetched page tables + manual double-buffered
page DMA).

Layouts:
  q            [B, n_heads, head_dim]
  k_pages, v_pages [n_kv_heads, num_pages, head_dim, page_size]
      (head_dim BEFORE page: a page's DMA slice then has trailing dims
      (head_dim, page) = (64|128, 128), which Mosaic can tile — with page
      last-minor the 64-wide head_dim would land on the 128-lane axis and
      the per-page slice fails to lower)
  lengths      [B]  number of valid tokens (attend positions < lengths)
  page_tables  [B, P]  page ids in position order (entry 0 = scratch page)

Returns [B, n_heads, head_dim].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -0.7 * float(np.finfo(np.float32).max)


def paged_decode_attention(q, k_pages, v_pages, lengths, page_tables, *,
                           interpret: bool | None = None):
    """Flash decode over paged KV; see module docstring for layouts.

    interpret=None auto-selects: the Mosaic lowering needs a real TPU
    backend; everywhere else (CPU tests, multichip dryrun) the kernel
    runs in interpret mode."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    page, hd = k_pages.shape[3], k_pages.shape[2]
    if not interpret and (page % 128 or hd % 8):
        # Mosaic can only DMA page slices whose trailing dims tile to
        # (8, 128); off-size pages (toy/test configs) fall back to the
        # XLA gather-attend formulation — slower, always correct.
        return _paged_decode_xla(q, k_pages, v_pages, lengths, page_tables)
    return _paged_decode_dma(q, k_pages, v_pages, lengths,
                             page_tables, interpret=interpret)


@jax.jit
def _paged_decode_xla(q, k_pages, v_pages, lengths, page_tables):
    return paged_decode_attention_reference(q, k_pages, v_pages, lengths,
                                            page_tables)


def _dma_kernel(lengths_ref, tables_ref,  # scalar prefetch (SMEM)
                q_ref, k_hbm, v_hbm, o_ref,
                kbuf, vbuf, m_ref, l_ref, acc_ref, sem, *, page: int,
                scale: float, pages_per_seq: int):
    """One grid step per slot; the slot's pages stream HBM->VMEM through
    a two-deep manual DMA pipeline (page i+1 in flight while page i is in
    the flash update). One grid step per slot keeps grid overhead off the
    hot path — a BlockSpec-per-page variant spends more time stepping the
    grid than computing (measured ~0.8ms per layer call vs ~0.2ms for
    this shape)."""
    b = pl.program_id(0)
    length = lengths_ref[b]
    npg = jnp.minimum(
        jax.lax.div(length + page - 1, page), pages_per_seq)

    def start_copy(i, slot):
        pid = tables_ref[b, i]
        pltpu.make_async_copy(
            k_hbm.at[:, pid], kbuf.at[slot], sem.at[slot, 0]).start()
        pltpu.make_async_copy(
            v_hbm.at[:, pid], vbuf.at[slot], sem.at[slot, 1]).start()

    def wait_copy(slot):
        pltpu.make_async_copy(
            k_hbm.at[:, 0], kbuf.at[slot], sem.at[slot, 0]).wait()
        pltpu.make_async_copy(
            v_hbm.at[:, 0], vbuf.at[slot], sem.at[slot, 1]).wait()

    m_ref[...] = jnp.full_like(m_ref, _NEG)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(npg > 0)
    def _first():
        start_copy(0, 0)

    q = q_ref[0].astype(jnp.float32)                   # [hkv, g, hd]
    hkv, g, hd = q.shape

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < npg)
        def _prefetch():
            start_copy(i + 1, 1 - slot)

        wait_copy(slot)
        k = kbuf[slot].astype(jnp.float32)             # [hkv, hd, page]
        v = vbuf[slot].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [hkv, g, page]
        pos = i * page + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=2)
        s = jnp.where(pos < length, s, _NEG)
        m_old = m_ref[...]                             # [hkv*g, 128]
        s2 = s.reshape(hkv * g, page)
        m_cur = jnp.max(s2, axis=1, keepdims=True)
        m_new = jnp.maximum(m_old, jnp.broadcast_to(m_cur, m_old.shape))
        alpha = jnp.exp(m_old[:, :1] - m_new[:, :1])
        p_exp = jnp.exp(s2 - m_new[:, :1])
        l_ref[...] = l_ref[...] * alpha + jnp.sum(
            p_exp, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p_exp.reshape(hkv, g, page), v,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [hkv, g, hd]
        acc_ref[...] = acc_ref[...] * alpha[:, None].reshape(
            hkv, g, 1) + pv
        m_ref[...] = m_new
        return 0

    jax.lax.fori_loop(0, npg, body, 0)
    l = l_ref[...][:, :1]
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc_ref[...] / l.reshape(hkv, g, 1)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_decode_dma(q, k_pages, v_pages, lengths, page_tables, *,
                      interpret: bool = False):
    B, h, hd = q.shape
    hkv, N, _, page = k_pages.shape
    assert h % hkv == 0, (h, hkv)
    g = h // hkv
    P = page_tables.shape[1]
    q4 = q.reshape(B, hkv, g, hd)
    scale = 1.0 / float(np.sqrt(hd))
    kernel = functools.partial(_dma_kernel, page=page, scale=scale,
                               pages_per_seq=P)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, hkv, g, hd),
                             lambda b, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec(memory_space=pltpu.ANY),   # k_pages in HBM
                pl.BlockSpec(memory_space=pltpu.ANY),   # v_pages in HBM
            ],
            out_specs=pl.BlockSpec((1, hkv, g, hd),
                                   lambda b, lens, tbl: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, hkv, hd, page), k_pages.dtype),  # kbuf
                pltpu.VMEM((2, hkv, hd, page), v_pages.dtype),  # vbuf
                pltpu.VMEM((hkv * g, 128), jnp.float32),        # m
                pltpu.VMEM((hkv * g, 128), jnp.float32),        # l
                pltpu.VMEM((hkv, g, hd), jnp.float32),          # acc
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, hkv, g, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(lengths, page_tables, q4, k_pages, v_pages)
    return out.reshape(B, h, hd)


def paged_decode_attention_reference(q, k_pages, v_pages, lengths,
                                     page_tables):
    """Dense reference for tests: gather pages, mask, softmax."""
    B, h, hd = q.shape
    hkv, N, _, page = k_pages.shape
    g = h // hkv
    P = page_tables.shape[1]
    T = P * page
    ck = k_pages[:, page_tables]          # [hkv, B, P, hd, page]
    cv = v_pages[:, page_tables]
    # -> [B, hkv, T, hd]
    ck = jnp.moveaxis(ck, 0, 1).transpose(0, 1, 2, 4, 3).reshape(
        B, hkv, T, hd)
    cv = jnp.moveaxis(cv, 0, 1).transpose(0, 1, 2, 4, 3).reshape(
        B, hkv, T, hd)
    q4 = q.reshape(B, hkv, g, hd).astype(jnp.float32)
    s = jnp.einsum("bkgd,bktd->bkgt", q4, ck.astype(jnp.float32))
    s = s / np.sqrt(hd)
    mask = jnp.arange(T)[None, None, None] < lengths[:, None, None, None]
    s = jnp.where(mask, s, -jnp.inf)
    pr = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgt,bktd->bkgd", pr, cv.astype(jnp.float32))
    return out.reshape(B, h, hd).astype(q.dtype)
