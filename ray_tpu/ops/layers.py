"""Transformer layer ops (jnp; XLA-fused on TPU).

Kept as plain jnp on purpose: RMSNorm/RoPE/SwiGLU are bandwidth-bound
elementwise chains that XLA fuses into neighboring matmuls; a Pallas kernel
here would only pin the schedule. fp32 accumulation where it matters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rmsnorm(x, weight, eps: float = 1e-6):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps)
    return (out * weight.astype(jnp.float32)).astype(dtype)


def rope(positions, head_dim: int, theta: float = 10000.0):
    """Rotary embedding tables. positions: [..., seq] -> (sin, cos) each
    [..., seq, head_dim/2]."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x, sin, cos):
    """x: [batch, seq, heads, head_dim]; sin/cos: [batch?, seq, head_dim/2]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    if sin.ndim == 2:  # [seq, half] -> broadcast over batch
        sin = sin[None]
        cos = cos[None]
    sin = sin[:, :, None, :]  # [batch, seq, 1, half]
    cos = cos[:, :, None, :]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def swiglu(x, w_gate, w_up, w_down):
    """SwiGLU MLP: silu(x@Wg) * (x@Wu) @ Wd. Outputs stay in x.dtype — the
    MXU accumulates in fp32 regardless, and fp32 outputs double HBM traffic
    and the AD-saved residual footprint."""
    g = jnp.einsum("bse,ef->bsf", x, w_gate)
    u = jnp.einsum("bse,ef->bsf", x, w_up)
    h = jax.nn.silu(g) * u
    return jnp.einsum("bsf,fe->bse", h, w_down)
