"""TPU kernels (Pallas) and fused ops.

Policy: XLA fuses elementwise chains into matmuls on its own — only ops where
a hand schedule beats the compiler get Pallas kernels (flash attention's
online-softmax tiling). Everything else stays jnp so the compiler keeps
freedom to fuse (SURVEY north-star: "let XLA fuse — don't hand-schedule what
the compiler already does").
"""

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.layers import rmsnorm, rope, apply_rope, swiglu

__all__ = ["flash_attention", "rmsnorm", "rope", "apply_rope", "swiglu"]
