"""Checkpoints: crash-consistent sharded directories + orbax pytree state.

Parity: reference `python/ray/train/_checkpoint.py:56` (Checkpoint = dir +
fs URI), `train/_internal/checkpoint_manager.py` (keep-top-K),
`train/_internal/storage.py:358` (StorageContext). TPU-first additions:

- `save_state/restore_state` use orbax (async-capable, sharding-aware), so
  a GSPMD-sharded TrainState checkpoints without gathering to one host,
  and `restore_state` with a resharded abstract target restores an N-way
  save onto an M-way mesh (the elastic re-mesh path).

- **Two-phase commit.** A distributed checkpoint directory is only valid
  once it carries a `MANIFEST.json`: every rank writes its shard
  (tmp+fsync+rename, so a shard file either exists complete or not at
  all), acks durability to the controller, and the controller commits the
  manifest — shard list + step + world size + dataset offsets — with the
  same tmp+fsync+rename dance, only after ALL ranks acked. A SIGKILL
  anywhere in the window leaves either a previous committed checkpoint
  (manifest present) or an uncommitted directory `gc_uncommitted` removes
  on restart; it can never leave a torn checkpoint that LOOKS resumable.

Shard naming is deterministic (`checkpoint_<step>` / `shard_R-of-W.pkl`),
so every rank of a gang converges on the same directory without
coordination, and a crashed attempt's re-run of the same step overwrites
its own debris.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any

MANIFEST_NAME = "MANIFEST.json"
_CKPT_PREFIX = "checkpoint_"


def _fsync_dir(path: str) -> None:
    # Directory fsync publishes the rename itself; ignore filesystems that
    # refuse to fsync a directory fd (the rename is still atomic there).
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp (same dir) + fsync + rename + dir fsync: `path` either holds
    the complete bytes or does not exist — never a torn prefix."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_" + os.path.basename(path))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(d)


def atomic_write_json(path: str, obj) -> None:
    atomic_write_bytes(path, json.dumps(obj).encode())


def shard_name(rank: int, world: int) -> str:
    return f"shard_{rank:05d}-of-{world:05d}.pkl"


def step_dir(storage_dir: str, step: int) -> str:
    """Deterministic per-step directory: all ranks converge on it with no
    coordination (the old time-ms suffix made every rank mint its own)."""
    return os.path.join(storage_dir, f"{_CKPT_PREFIX}{int(step):06d}")


def write_shard(data: dict, ckpt_dir: str, rank: int, world: int) -> str:
    """Durably write one rank's state shard; returns the shard file name.
    The shard is complete-or-absent (atomic_write_bytes)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    name = shard_name(rank, world)
    atomic_write_bytes(os.path.join(ckpt_dir, name),
                       pickle.dumps(data, protocol=5))
    return name


def commit_manifest(ckpt_dir: str, *, step: int, world_size: int,
                    shards: list[str], dataset_offsets: dict | None = None,
                    mesh_shape: dict | None = None,
                    arena: dict | None = None,
                    extra: dict | None = None) -> str:
    """Phase 2: publish the checkpoint. Called by the controller only
    after every rank acked a durable shard; the manifest rename is the
    commit point — `latest_ckpt_path` may only ever advance to a
    directory whose manifest exists."""
    manifest = {
        "step": int(step),
        "world_size": int(world_size),
        "shards": list(shards),
        "dataset_offsets": dict(dataset_offsets or {}),
        "mesh_shape": dict(mesh_shape or {}),
        # rank -> arena object id hex: surviving peers restore shards over
        # striped objxfer pulls instead of shared disk (best-effort; disk
        # stays the source of truth).
        "arena": dict(arena or {}),
        "committed_at": time.time(),
    }
    if extra:
        manifest.update(extra)
    atomic_write_json(os.path.join(ckpt_dir, MANIFEST_NAME), manifest)
    return ckpt_dir


def load_manifest(path: str) -> dict | None:
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_committed(path: str) -> bool:
    """Committed = manifest present, or the legacy single-file layout
    (data.pkl — its atomic rename IS that layout's commit point)."""
    return (os.path.exists(os.path.join(path, MANIFEST_NAME))
            or os.path.exists(os.path.join(path, "data.pkl")))


def latest_committed(storage_dir: str) -> str | None:
    """Highest-step committed checkpoint dir under storage_dir, or None."""
    best: tuple[int, str] | None = None
    try:
        names = os.listdir(storage_dir)
    except OSError:
        return None
    for name in names:
        if not name.startswith(_CKPT_PREFIX):
            continue
        path = os.path.join(storage_dir, name)
        if not os.path.isdir(path) or not is_committed(path):
            continue
        m = load_manifest(path)
        step = (m or {}).get("step")
        if step is None:
            # Legacy dir: fall back to the name's step field.
            try:
                step = int(name[len(_CKPT_PREFIX):].split("_")[0])
            except ValueError:
                step = -1
        if best is None or step > best[0]:
            best = (step, path)
    return best[1] if best else None


def gc_uncommitted(storage_dir: str) -> list[str]:
    """Remove checkpoint dirs that never committed (no manifest, no legacy
    data.pkl) — the debris a crash leaves between shard writes and the
    manifest rename. Run at (re)start, when no writer can be mid-flight.
    Returns the removed paths."""
    removed = []
    try:
        names = os.listdir(storage_dir)
    except OSError:
        return removed
    for name in names:
        path = os.path.join(storage_dir, name)
        if not (name.startswith(_CKPT_PREFIX) and os.path.isdir(path)):
            continue
        if not is_committed(path):
            shutil.rmtree(path, ignore_errors=True)
            removed.append(path)
    return removed


class Checkpoint:
    """A handle to a checkpoint directory (legacy single-file or sharded
    manifest layout)."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_dict(cls, data: dict, storage_dir: str, step: int = 0) -> "Checkpoint":
        """Single-writer convenience: a world-size-1 sharded checkpoint,
        committed on the spot (write shard, then manifest)."""
        path = step_dir(storage_dir, step)
        name = write_shard(data, path, 0, 1)
        commit_manifest(path, step=step, world_size=1, shards=[name])
        return cls(path)

    def manifest(self) -> dict | None:
        return load_manifest(self.path)

    def is_committed(self) -> bool:
        return is_committed(self.path)

    def to_dict(self) -> dict:
        """The rank-0 shard (legacy surface: with one writer — or
        DP-replicated state — this IS the state)."""
        legacy = os.path.join(self.path, "data.pkl")
        if os.path.exists(legacy):
            with open(legacy, "rb") as f:
                return pickle.load(f)
        return self.load_shard(0)

    def load_shard(self, rank: int, world: int | None = None) -> dict:
        """Shard for `rank` under a (possibly different) restore world
        size. An N-way save restored at world M maps rank r to saved
        shard r % N — exact for DP-replicated dict state; genuinely
        sharded pytrees reshard through the orbax plane
        (`restore_state` with a resharded abstract target) instead.
        Tries the manifest's arena object first (objxfer pull from a
        surviving peer), then shared disk."""
        m = self.manifest()
        if m is None:
            raise FileNotFoundError(
                f"{self.path} has no committed manifest — uncommitted "
                "checkpoints are not restorable (gc_uncommitted removes "
                "them at restart)")
        n = m["world_size"]
        if not m["shards"]:
            raise FileNotFoundError(
                f"{self.path} committed without dict shards (externally "
                "written state, e.g. an orbax save_state dir) — restore "
                "it with checkpoint.restore_state, not load_shard")
        src = rank % n if n else 0
        data = self._load_shard_arena(m, src)
        if data is not None:
            return data
        with open(os.path.join(self.path, m["shards"][src]), "rb") as f:
            return pickle.load(f)

    def _load_shard_arena(self, manifest: dict, src_rank: int):
        """Best-effort arena restore: the manifest's sealed shard object,
        pulled over the object plane (PR 7 striped pulls cross-node). Any
        failure — no runtime, object evicted, owner gone — falls back to
        the disk shard."""
        hex_id = (manifest.get("arena") or {}).get(str(src_rank))
        if not hex_id:
            return None
        try:
            import ray_tpu
            from ray_tpu.core.ids import ObjectID
            from ray_tpu.core.object_ref import ObjectRef
            from ray_tpu.core.runtime import current_runtime
            if current_runtime() is None:
                return None
            ref = ObjectRef(ObjectID.from_hex(hex_id), _add_ref=False)
            # Short deadline: the common miss is an object freed with its
            # dead owner — waiting a long resolution timeout on EVERY
            # rank's restore would slow the restart the arena path exists
            # to speed up.
            return ray_tpu.get(ref, timeout=1)
        except Exception:  # noqa: BLE001 — disk is the source of truth
            return None

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def __repr__(self):
        return f"Checkpoint({self.path})"


def save_state(state, path: str):
    """Orbax save of a (possibly sharded) pytree; gathers per-shard files."""
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), state, force=True)
    ckptr.wait_until_finished()


def restore_state(path: str, target=None):
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.abspath(path), target)


def abstract_state(template, shardings):
    """Abstract restore target: the template's shapes/dtypes with NEW
    shardings attached — hand it to `restore_state` to reshard an N-way
    orbax save onto an M-way mesh (orbax assembles each array straight
    into the target sharding; no N-way gather materializes)."""
    import jax

    def leaf(x, s):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        return jax.ShapeDtypeStruct(shape, dtype, sharding=s)

    return jax.tree.map(leaf, template, shardings)


class CheckpointManager:
    """Keep-top-K checkpoint retention with a metrics index."""

    def __init__(self, storage_dir: str, keep: int = 2,
                 metric: str | None = None, mode: str = "min"):
        self.storage_dir = storage_dir
        self.keep = keep
        self.metric = metric
        self.mode = mode
        self.entries: list[tuple[float, str]] = []
        self.latest_committed_path: str | None = None
        os.makedirs(storage_dir, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: dict | None = None):
        score = 0.0
        if self.metric and metrics and self.metric in metrics:
            score = float(metrics[self.metric])
            if self.mode == "max":
                score = -score
        else:
            score = -time.time()  # newest wins
        if checkpoint.is_committed():
            self.latest_committed_path = checkpoint.path
        # Re-registration (a restart re-commits the step it resumed at)
        # replaces the old entry: duplicate entries would let keep-K
        # evict a path that is still tracked live.
        self.entries = [e for e in self.entries if e[1] != checkpoint.path]
        self.entries.append((score, checkpoint.path))
        self.entries.sort()
        while len(self.entries) > self.keep:
            victim_i = len(self.entries) - 1
            # Never evict the latest COMMITTED checkpoint, even when the
            # keep-K metric scoring ranks it worst: it is the only state a
            # crash right now is provably able to resume from.
            if self.entries[victim_i][1] == self.latest_committed_path:
                victim_i -= 1
            if victim_i < 0:
                break
            _, path = self.entries.pop(victim_i)
            shutil.rmtree(path, ignore_errors=True)
        self._write_index(metrics)

    def _write_index(self, metrics):
        atomic_write_json(os.path.join(self.storage_dir, "index.json"),
                          {"checkpoints": [p for _, p in self.entries],
                           "latest_metrics": metrics})

    def best(self) -> Checkpoint | None:
        return Checkpoint(self.entries[0][1]) if self.entries else None
