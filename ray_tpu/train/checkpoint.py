"""Checkpoints: directory handles + orbax-backed pytree state.

Parity: reference `python/ray/train/_checkpoint.py:56` (Checkpoint = dir +
fs URI), `train/_internal/checkpoint_manager.py` (keep-top-K),
`train/_internal/storage.py:358` (StorageContext). TPU-first addition:
`save_state/restore_state` use orbax (async-capable, sharding-aware), so a
GSPMD-sharded TrainState checkpoints without gathering to one host.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
from typing import Any


class Checkpoint:
    """A handle to a checkpoint directory."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_dict(cls, data: dict, storage_dir: str, step: int = 0) -> "Checkpoint":
        path = os.path.join(storage_dir, f"checkpoint_{step:06d}_{int(time.time()*1e3)}")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "data.pkl"), "wb") as f:
            pickle.dump(data, f, protocol=5)
        return cls(path)

    def to_dict(self) -> dict:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def __repr__(self):
        return f"Checkpoint({self.path})"


def save_state(state, path: str):
    """Orbax save of a (possibly sharded) pytree; gathers per-shard files."""
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), state, force=True)
    ckptr.wait_until_finished()


def restore_state(path: str, target=None):
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.abspath(path), target)


class CheckpointManager:
    """Keep-top-K checkpoint retention with a metrics index."""

    def __init__(self, storage_dir: str, keep: int = 2,
                 metric: str | None = None, mode: str = "min"):
        self.storage_dir = storage_dir
        self.keep = keep
        self.metric = metric
        self.mode = mode
        self.entries: list[tuple[float, str]] = []
        os.makedirs(storage_dir, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: dict | None = None):
        score = 0.0
        if self.metric and metrics and self.metric in metrics:
            score = float(metrics[self.metric])
            if self.mode == "max":
                score = -score
        else:
            score = -time.time()  # newest wins
        self.entries.append((score, checkpoint.path))
        self.entries.sort()
        while len(self.entries) > self.keep:
            _, path = self.entries.pop()
            shutil.rmtree(path, ignore_errors=True)
        self._write_index(metrics)

    def _write_index(self, metrics):
        with open(os.path.join(self.storage_dir, "index.json"), "w") as f:
            json.dump({"checkpoints": [p for _, p in self.entries],
                       "latest_metrics": metrics}, f)

    def best(self) -> Checkpoint | None:
        return Checkpoint(self.entries[0][1]) if self.entries else None
