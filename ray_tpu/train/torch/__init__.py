"""TorchTrainer: the reference's flagship trainer surface, on this runtime.

Parity: reference `train/torch/torch_trainer.py:11` (TorchTrainer),
`train/torch/config.py` (TorchConfig -> dist.init_process_group) and
`train/torch/train_loop_utils.py` (prepare_model / prepare_data_loader).

Role in a TPU-first framework: the migration path. Users arriving from the
reference keep their torch training loops running (CPU gloo DDP across
worker actors on this runtime) while porting the model to JaxTrainer for
the TPU compute path — torch-on-TPU (torch-xla) is not shipped in this
environment, so `get_device()` is CPU and the speed lives in JaxTrainer.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from ray_tpu.train.backend import Backend
from ray_tpu.train.trainer import (
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


@dataclasses.dataclass
class TorchConfig(Backend):
    """Parity: train/torch/config.py TorchConfig."""

    backend: str = "gloo"          # CPU image: gloo (nccl has no place here)
    init_timeout_s: float = 120.0

    needs_coordinator = True

    def on_worker_start(self, rank: int, world_size: int,
                        coordinator: str | None):
        if world_size <= 1 or coordinator is None:
            return  # single worker: no process group needed
        import datetime

        import torch.distributed as dist
        if dist.is_initialized():
            return
        dist.init_process_group(
            backend=self.backend,
            init_method=f"tcp://{coordinator}",
            rank=rank, world_size=world_size,
            timeout=datetime.timedelta(seconds=self.init_timeout_s))

    def on_worker_shutdown(self):
        import torch.distributed as dist
        if dist.is_initialized():
            dist.destroy_process_group()


class TorchTrainer(JaxTrainer):
    """Same controller/worker-group/failure machinery as JaxTrainer, with a
    torch process-group backend set up before the user loop."""

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: dict | None = None,
                 torch_config: TorchConfig | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 resume_from_checkpoint=None):
        super().__init__(train_loop_per_worker,
                         train_loop_config=train_loop_config,
                         scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.backend = torch_config or TorchConfig()


def get_device():
    """Parity: ray.train.torch.get_device (CPU in this environment)."""
    import torch
    return torch.device("cpu")


def prepare_model(model, *, wrap_ddp: bool = True):
    """Wrap the model for the worker group (parity: train_loop_utils.py
    prepare_model): DDP when a multi-worker process group is up."""
    import torch.distributed as dist
    if wrap_ddp and dist.is_available() and dist.is_initialized() \
            and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel
        return DistributedDataParallel(model)
    return model


class _EpochSteppingLoader:
    """DataLoader wrapper that bumps DistributedSampler.set_epoch on every
    full iteration, so multi-epoch loops reshuffle per epoch without the
    user having to call set_epoch themselves (the reference's
    prepare_data_loader wraps the iterator the same way)."""

    def __init__(self, loader, sampler):
        self._loader = loader
        self._sampler = sampler
        self._epoch = 0

    def __iter__(self):
        self._sampler.set_epoch(self._epoch)
        self._epoch += 1
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, name):
        return getattr(self._loader, name)


def prepare_data_loader(data_loader):
    """Shard a DataLoader across the worker group with a DistributedSampler
    (parity: train_loop_utils.py prepare_data_loader).

    Loaders that already carry a custom sampling scheme are left alone: a
    batch_sampler= loader (batch_size is None) or a non-default sampler
    (e.g. WeightedRandomSampler) cannot be re-sharded without changing the
    user's sampling distribution."""
    import torch.distributed as dist
    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    from torch.utils.data import (
        DataLoader,
        RandomSampler,
        SequentialSampler,
    )
    from torch.utils.data.distributed import DistributedSampler
    if data_loader.batch_size is None:  # batch_sampler= construction
        return data_loader
    if not isinstance(data_loader.sampler,
                      (RandomSampler, SequentialSampler)):
        return data_loader  # custom sampler: keep the user's distribution
    shuffle = isinstance(data_loader.sampler, RandomSampler)
    sampler = DistributedSampler(data_loader.dataset,
                                 num_replicas=dist.get_world_size(),
                                 rank=dist.get_rank(), shuffle=shuffle)
    kw = dict(
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last,
        timeout=data_loader.timeout,
        worker_init_fn=data_loader.worker_init_fn,
        generator=data_loader.generator,
        persistent_workers=data_loader.persistent_workers,
        multiprocessing_context=data_loader.multiprocessing_context,
    )
    if data_loader.num_workers > 0:  # only valid with loader workers
        kw["prefetch_factor"] = data_loader.prefetch_factor
    loader = DataLoader(data_loader.dataset, **kw)
    return _EpochSteppingLoader(loader, sampler)


__all__ = ["TorchTrainer", "TorchConfig", "get_device", "prepare_model",
           "prepare_data_loader"]
