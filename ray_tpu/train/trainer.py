"""JaxTrainer: controller + worker-group actors running SPMD JAX.

Parity: reference Train v2 (`TrainController` FSM
`v2/_internal/execution/controller/controller.py:91`, worker group
`v2/.../worker_group/worker_group.py`, `FailurePolicy`
`failure_handling/failure_policy.py:14`) and the v1 `BackendExecutor`
(`train/_internal/backend_executor.py:73`).

TPU-first architecture (SURVEY §7 design stance): ONE worker actor per HOST,
not per chip — each worker owns all local TPU chips and enters the same
jit-compiled GSPMD program; multi-host meshes are formed with
jax.distributed (coordinator = worker 0). DP/FSDP/TP/SP/EP happen INSIDE the
program via shardings, so there is no NCCL-style process group to babysit:
the "backend setup" the reference does in `train/torch/config.py` reduces to
jax.distributed.initialize + mesh construction.

Elastic resume (ROADMAP item 3): checkpoints are two-phase-committed
(train/checkpoint.py — every rank's durable shard ack, THEN the controller's
manifest rename), `latest_ckpt_path` advances only on committed manifests,
and a worker death restarts the gang at whatever world size the cluster
still fits (>= min_workers), resharding state and re-splitting datasets from
the manifest's recorded offsets. A wedged-not-dead worker is converted into
the same restart by the poll/progress watchdogs instead of stalling the run.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
import traceback
from typing import Any, Callable

import ray_tpu
from ray_tpu.core import chaos
from ray_tpu.core.status import GetTimeoutError, RayTpuError


def _train_knob(name: str, override=None) -> float:
    """RunConfig override first, then the cluster config knob."""
    if override is not None:
        return override
    from ray_tpu.core.config import get_config
    return getattr(get_config(), name)


@dataclasses.dataclass
class ScalingConfig:
    """Parity: ray.train.ScalingConfig (air/config.py)."""

    num_workers: int = 1          # = number of hosts in the mesh
    use_tpu: bool = False
    resources_per_worker: dict | None = None
    chips_per_worker: int = 0     # 0 = all chips on the host
    # Elastic lower bound (parity: Train v2 ScalingPolicy,
    # scaling_policy.py:29): None = fixed size; set to let a run start (or
    # RESTART after failures/preemptions) with however many workers
    # currently fit the cluster, down to this floor. TPU fleets are
    # preemption-heavy — resuming smaller beats not resuming.
    min_workers: int | None = None


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    name: str = "train_run"
    storage_path: str | None = None
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    checkpoint_keep: int = 2
    # Per-run overrides for the train_* config knobs (None = knob value):
    # one poll round-trip deadline, the no-progress gang watchdog, and the
    # restart capacity-settle wait.
    poll_timeout_s: float | None = None
    progress_timeout_s: float | None = None
    restart_wait_s: float | None = None


@dataclasses.dataclass
class Result:
    """Parity: ray.air.Result."""

    metrics: dict
    checkpoint: Any
    path: str
    error: BaseException | None = None
    metrics_history: list = dataclasses.field(default_factory=list)


class TrainWorker:
    """Actor hosting the user training loop (one per host)."""

    def __init__(self, rank: int, world_size: int, storage_dir: str,
                 coordinator: str | None, env: dict,
                 backend_bytes: bytes | None = None):
        os.environ.update(env)
        self.rank = rank
        self.world_size = world_size
        self.storage_dir = storage_dir
        self.coordinator = coordinator
        self._thread = None
        self._session = None
        self.local_rank = 0
        self.local_world_size = 1
        self._backend = None
        if backend_bytes is not None:
            import cloudpickle
            self._backend = cloudpickle.loads(backend_bytes)

    def get_address(self) -> str:
        """Rendezvous address minted on THIS worker's node (rank 0 binds
        it), so multi-node gangs don't chase the controller's loopback."""
        import socket
        ip = None
        try:
            # Outbound-route probe: a UDP connect sends no packets but
            # resolves the interface IP other nodes can reach — hostname
            # lookup often lands on 127.0.1.1 (Debian /etc/hosts).
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                probe.connect(("8.8.8.8", 80))
                ip = probe.getsockname()[0]
            finally:
                probe.close()
        except OSError:
            pass
        if ip is None or ip.startswith("127."):
            try:
                ip = socket.gethostbyname(socket.gethostname())
            except OSError:
                ip = "127.0.0.1"
        # Probe-bind BELOW the kernel's ephemeral floor: bind(0) mints a
        # port from the ephemeral range (net.ipv4.ip_local_port_range,
        # 32768+ by default), which any unrelated outgoing connection can
        # grab in the close -> torch-rebind window — the EADDRINUSE flake
        # on a busy host. A sub-ephemeral port can only lose a race to
        # another deliberate binder, and the pid-spread start keeps
        # concurrent gangs on disjoint probes.
        bind_ip = "" if ip.startswith("127.") else ip
        base, span = 20000, 8000
        start = (os.getpid() * 97) % span
        for off in range(512):
            port = base + (start + off) % span
            s = socket.socket()
            try:
                s.bind((bind_ip, port))
            except OSError:
                s.close()
                continue
            s.close()
            return f"{ip}:{port}"
        s = socket.socket()  # range exhausted (pathological): old path
        s.bind((bind_ip, 0))
        port = s.getsockname()[1]
        s.close()
        return f"{ip}:{port}"

    def get_node_id(self) -> str:
        import ray_tpu
        return ray_tpu.get_node_id()

    def set_local_rank(self, local_rank: int, local_world_size: int):
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        return True

    def setup_distributed(self, coordinator: str | None = None):
        """Join the gang via the framework Backend hook (torch process
        group, JaxDistributedConfig multi-host jax); no-op without one."""
        if coordinator is not None:
            self.coordinator = coordinator
        if self._backend is not None:
            self._backend.on_worker_start(self.rank, self.world_size,
                                          self.coordinator)
        return self.rank

    def run(self, loop_fn_bytes: bytes, loop_config: dict,
            checkpoint_path: str | None, dataset_shards: dict | None = None,
            dataset_offsets: dict | None = None):
        import cloudpickle
        from ray_tpu.train import session as session_mod
        from ray_tpu.train.checkpoint import Checkpoint
        loop_fn = cloudpickle.loads(loop_fn_bytes)
        ckpt = Checkpoint(checkpoint_path) if checkpoint_path else None
        self._session = session_mod.TrainSession(
            self.rank, self.world_size, self.storage_dir, checkpoint=ckpt,
            dataset_shards=dataset_shards, local_rank=self.local_rank,
            local_world_size=self.local_world_size,
            dataset_offsets=dataset_offsets)
        session_mod._set_session(self._session)

        def target():
            try:
                loop_fn(loop_config)
            except BaseException as e:  # noqa: BLE001 — ship to controller
                self._session.error = e
                self._session.reports.append(
                    {"error": traceback.format_exc(), "rank": self.rank})
            finally:
                self._session.finished = True

        self._thread = threading.Thread(target=target, daemon=True)
        self._thread.start()
        return True

    def poll(self):
        """Controller heartbeat: (reports, finished, error_str)."""
        if chaos.site("train.poll_hang"):
            # Wedged-not-dead: the actor thread hangs without the process
            # dying — the shape a stuck collective / NFS stall takes. The
            # controller's poll deadline must convert this into a restart.
            time.sleep(3600)
        s = self._session
        if s is None:
            return [], False, None
        # Read finished BEFORE draining: the loop thread appends its final
        # report before setting finished, so this order can't lose it.
        finished = s.finished
        reports = s.drain_reports()
        err = None
        if s.error is not None:
            err = repr(s.error)
        return reports, finished, err

    def latest_checkpoint_path(self):
        s = self._session
        if s and s.latest_checkpoint:
            return s.latest_checkpoint.path
        return None

    def shutdown(self):
        if self._backend is not None:
            try:
                self._backend.on_worker_shutdown()
            except Exception:  # noqa: BLE001 — teardown is best effort
                pass
        return True


# controller states (parity: TrainControllerState in v2 controller.py)
INIT, RUNNING, RESTARTING, FINISHED, ERRORED = (
    "INITIALIZING", "RUNNING", "RESTARTING", "FINISHED", "ERRORED")


class _PendingCommit:
    """Phase-2 state for one (dir, step): which ranks acked a durable
    shard, plus the manifest payload accumulated from the acks."""

    __slots__ = ("step", "world", "acks", "shards", "arena", "offsets")

    def __init__(self, step: int, world: int):
        self.step = step
        self.world = world
        self.acks: set[int] = set()
        self.shards: dict[int, str] = {}
        self.arena: dict[str, str] = {}
        self.offsets: dict = {}


class JaxTrainer:
    """Parity: TorchTrainer (`train/torch/torch_trainer.py:11`) +
    DataParallelTrainer (`data_parallel_trainer.py:26`), TPU-native."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 resume_from_checkpoint=None,
                 jax_config=None):
        self.train_loop = train_loop_per_worker
        self.loop_config = train_loop_config or {}
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        # Framework Backend: TorchTrainer sets TorchConfig; pass
        # jax_config=JaxDistributedConfig() for a cross-host SPMD gang.
        self.backend = jax_config
        self.state = INIT

    def _storage_dir(self) -> str:
        base = self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_train")
        path = os.path.join(base, self.run_config.name)
        os.makedirs(path, exist_ok=True)
        return path

    def _per_worker_req(self) -> dict:
        """Every resource one worker consumes (custom resources included) —
        the ONE definition sizing and group creation both use."""
        res = dict(self.scaling.resources_per_worker or {})
        req = dict(res)
        req["CPU"] = res.get("CPU", 1)
        tpus = res.get("TPU", self.scaling.chips_per_worker
                       if self.scaling.use_tpu else 0)
        if tpus:
            req["TPU"] = tpus
        else:
            req.pop("TPU", None)
        return req

    def _fit_now(self) -> int:
        """Workers placeable RIGHT NOW, summed per node (aggregate totals
        would mis-fit fragmented clusters: 4+4 free TPUs cannot host an
        8-TPU worker)."""
        req = {k: v for k, v in self._per_worker_req().items() if v > 0}
        if not req:
            # Zero-resource workers (co-location pattern): nothing bounds
            # placement, so the full requested size always fits.
            return self.scaling.num_workers
        total = 0
        for row in ray_tpu.nodes():
            if not row["alive"]:
                continue
            avail = row["available"]
            total += min(int(avail.get(k, 0.0) // v)
                         for k, v in req.items())
        return total

    def _elastic_size(self, wait_s: float = 0.0) -> int:
        """Workers for this (re)start: fixed, or fitted to what the cluster
        offers (elastic ScalingPolicy). On restarts the previous gang's
        kills release resources asynchronously — wait for capacity to
        settle (through the shared backoff policy, not a hot 100ms poll)
        instead of snapshotting mid-teardown and shrinking to the floor
        for no reason."""
        from ray_tpu.core.retry import Backoff
        n = self.scaling.num_workers
        lo = self.scaling.min_workers
        if lo is None:
            return n
        best = self._fit_now()
        if best < n:
            # Capacity-wait is the autoscaler's scale-UP signal (the
            # counterpart to the shrink loop): post the missing workers'
            # bundles so the policy core can launch slice-shaped nodes
            # while we wait.
            self._request_scale_up(n - best)
        if wait_s > 0:
            bo = Backoff(deadline_s=wait_s)
            while best < n and bo.sleep():
                best = max(best, self._fit_now())
        if best < lo:
            from ray_tpu.core.status import ResourceError
            raise ResourceError(
                f"elastic run needs at least min_workers={lo} x "
                f"{self._per_worker_req()} but the cluster currently fits "
                f"{best} (fail-fast beats burning the failure budget on "
                f"placement timeouts)")
        return min(best, n)

    def _request_scale_up(self, missing: int) -> None:
        """Post `missing` per-worker bundles to the head's scale-request
        queue (drained by autoscaler/policy.py). Works from the driver
        (direct Runtime call) and from workers (head request); a
        pre-autoscaler head just ignores it."""
        req = {k: v for k, v in self._per_worker_req().items() if v > 0}
        if not req:
            return
        bundles = [dict(req) for _ in range(max(1, int(missing)))]
        try:
            from ray_tpu.core.runtime import Runtime, get_runtime
            rt = get_runtime()
            if isinstance(rt, Runtime):
                rt.request_scale_up(bundles, source="train.capacity_wait")
            else:
                rt.request("scale_up", (bundles, "train.capacity_wait"),
                           timeout=10.0)
        except Exception:  # noqa: BLE001 — a hint, never a failure
            pass

    def _make_group(self, storage_dir: str, n: int):
        req = self._per_worker_req()
        num_cpus = req.get("CPU", 1)
        num_tpus = req.get("TPU", 0)
        custom = {k: v for k, v in req.items() if k not in ("CPU", "TPU")}
        env = {}
        backend_bytes = None
        needs_coordinator = n > 1 and (
            getattr(self.backend, "needs_coordinator", False))
        if self.backend is not None:
            import cloudpickle
            backend_bytes = cloudpickle.dumps(self.backend)
        WorkerCls = ray_tpu.remote(TrainWorker).options(
            num_cpus=num_cpus, num_tpus=num_tpus,
            resources=custom or None)
        workers = [
            WorkerCls.remote(rank=i, world_size=n, storage_dir=storage_dir,
                             coordinator=None, env=env,
                             backend_bytes=backend_bytes)
            for i in range(n)
        ]
        try:
            # Local ranks: position of each worker among the workers
            # co-located on its node (torch-style LOCAL_RANK semantics).
            node_ids = ray_tpu.get(
                [w.get_node_id.remote() for w in workers], timeout=60)
            per_node: dict = {}
            assignments = []
            for nid in node_ids:
                assignments.append(per_node.get(nid, 0))
                per_node[nid] = per_node.get(nid, 0) + 1
            ray_tpu.get(
                [w.set_local_rank.remote(assignments[i],
                                         per_node[node_ids[i]])
                 for i, w in enumerate(workers)], timeout=60)
            coordinator = None
            if needs_coordinator:
                # Rank 0 mints the rendezvous address on ITS node — it is
                # the process that binds it.
                coordinator = ray_tpu.get(
                    workers[0].get_address.remote(), timeout=60)
            # Gang rendezvous (SPMD impedance, SURVEY §7 hard-part 3).
            ray_tpu.get([w.setup_distributed.remote(coordinator)
                         for w in workers], timeout=300)
        except BaseException:
            # A partial gang must not leak: surviving actors would hold
            # their reservations forever and starve every retry.
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:  # noqa: BLE001
                    pass
            raise
        return workers

    def _resume_path(self, latest_ckpt_path: str | None) -> str | None:
        """The path the NEXT gang resumes from: committed manifests only.
        An uncommitted or torn directory (possible only for caller-supplied
        resume_from_checkpoint — in-run paths advance on commit) is refused
        loudly: resuming from state that merely LOOKS complete is the bug
        this plane exists to kill."""
        if latest_ckpt_path is None:
            return None
        from ray_tpu.train import checkpoint as ckpt_mod
        if not ckpt_mod.is_committed(latest_ckpt_path):
            raise RayTpuError(
                f"checkpoint {latest_ckpt_path} has no committed manifest "
                "(torn or abandoned write); refusing to resume from it")
        return latest_ckpt_path

    def fit(self) -> Result:
        import cloudpickle
        from ray_tpu.train import checkpoint as ckpt_mod
        storage_dir = self._storage_dir()
        _register_run(self)
        loop_bytes = cloudpickle.dumps(self.train_loop)
        failures_left = self.run_config.failure_config.max_failures
        resume_path = (self.resume_from_checkpoint.path
                       if self.resume_from_checkpoint else None)
        history: list[dict] = []
        latest_metrics: dict = {}
        # Held on self, not a local: _poll_until_done commits checkpoints
        # as acks arrive and may then RAISE on a worker death — a local
        # would forget every commit of the crashed attempt and restart
        # the run from scratch instead of the last committed step.
        self._latest_committed = self._resume_path(resume_path)
        self._ckpt_mgr = ckpt_mod.CheckpointManager(
            storage_dir, keep=self.run_config.checkpoint_keep)

        first_start = True
        while True:
            self.state = RUNNING
            # A crashed attempt's debris (shards written, manifest never
            # committed) must not survive into this attempt: no writer can
            # be mid-flight here, so uncommitted dirs are garbage.
            ckpt_mod.gc_uncommitted(storage_dir)
            try:
                # Restarts wait for the previous gang's resources to
                # release first.
                n = self._elastic_size(
                    wait_s=0.0 if first_start else _train_knob(
                        "train_restart_wait_s",
                        self.run_config.restart_wait_s))
            except RayTpuError as e:
                if first_start:
                    raise  # misconfigured from the start: surface raw
                # Below the elastic floor on a RESTART: end the run with
                # the normal Result contract (error + last checkpoint +
                # history) instead of leaking a raw exception.
                self.state = ERRORED
                _finalize_run(self)
                from ray_tpu.train.checkpoint import Checkpoint
                return Result(
                    metrics=latest_metrics,
                    checkpoint=Checkpoint(self._latest_committed)
                    if self._latest_committed else None,
                    path=storage_dir, error=e, metrics_history=history)
            first_start = False
            error = None
            workers = []
            try:
                # Group setup and gang start can also lose a worker (crash
                # in the first steps races the start RPC; a shrunk cluster
                # can kill placement) — all of it is FailurePolicy territory.
                workers = self._make_group(storage_dir, n)
                shards, offsets = self._split_datasets(
                    n, self._latest_committed)
                ray_tpu.get([
                    w.run.remote(loop_bytes, self.loop_config,
                                 self._latest_committed, shards[i], offsets)
                    for i, w in enumerate(workers)], timeout=300)
            except _WorkerGroupError as e:
                error = e
            except ray_tpu.RayTpuError as e:
                error = _WorkerGroupError(f"worker group start failed: {e}")
            try:
                if error is not None:
                    raise error
                # _poll_until_done appends into `history` in place, so
                # reports from an attempt that later crashes still reach
                # the Result (and the dashboard).
                latest_metrics = self._poll_until_done(workers, history)
                self.state = FINISHED
            except _WorkerGroupError as e:
                error = e
            # Backend teardown hook (best effort, bounded), then hard kill.
            # A HUNG group gets no grace (its poll already ate the poll
            # deadline once), and an already-broken group gets one second,
            # not five: restart latency is the recovery metric.
            if workers and not isinstance(error, _WorkerGroupHung):
                try:
                    ray_tpu.get([w.shutdown.remote() for w in workers],
                                timeout=5 if error is None else 1)
                except Exception:  # noqa: BLE001 — wedged workers
                    pass
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            if error is None:
                break
            # FailurePolicy: restart the whole gang from the last
            # COMMITTED checkpoint (latest_ckpt_path only ever advances on
            # manifest commits).
            if failures_left > 0:
                failures_left -= 1
                self.state = RESTARTING
                _finalize_run(self)
                continue
            self.state = ERRORED
            _finalize_run(self)
            from ray_tpu.train.checkpoint import Checkpoint
            return Result(metrics=latest_metrics,
                          checkpoint=Checkpoint(self._latest_committed)
                          if self._latest_committed else None,
                          path=storage_dir, error=error,
                          metrics_history=history)

        _finalize_run(self)
        from ray_tpu.train.checkpoint import Checkpoint
        return Result(
            metrics=latest_metrics,
            checkpoint=Checkpoint(self._latest_committed)
            if self._latest_committed else None,
            path=storage_dir, metrics_history=history)

    def _split_datasets(self, n: int, latest_ckpt_path: str | None = None):
        """Per-worker dataset shards (parity: get_dataset_shard/
        streaming_split). Equal-row shards: lockstep SPMD loops need
        identical iteration counts per rank (streaming_split(equal=True)
        semantics — a ragged shard would hang a collective at epoch end).

        Elastic resume: the committed manifest records per-dataset row
        offsets (reported by rank 0 alongside its checkpoint); rows before
        the offset were consumed pre-crash, so the new gang — possibly a
        different world size — re-splits only the remainder."""
        offsets: dict = {}
        if latest_ckpt_path:
            from ray_tpu.train import checkpoint as ckpt_mod
            m = ckpt_mod.load_manifest(latest_ckpt_path)
            offsets = dict((m or {}).get("dataset_offsets") or {})
        shards = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            off = int(offsets.get(name, 0))
            if off > 0 and hasattr(ds, "split_at_indices"):
                ds = ds.split_at_indices([off])[1]
            if hasattr(ds, "split"):
                parts = ds.split(n, equal=True)
            else:
                parts = [ds] * n
            for i in range(n):
                shards[i][name] = parts[i]
        return shards, offsets

    def _commit_if_ready(self, pending: "_PendingCommit", ckpt_dir: str,
                         latest_metrics: dict) -> bool:
        """Phase 2: all ranks acked durable shards -> rename the manifest
        in. Returns True when the checkpoint committed (the ONLY event
        that advances latest_ckpt_path)."""
        from ray_tpu.train import checkpoint as ckpt_mod
        if len(pending.acks) < pending.world:
            return False
        if chaos.site("train.manifest_loss"):
            # Controller crash window: every shard is durable but the
            # manifest rename never happens — the step must be invisible
            # to restarts (gc'd), and resume comes from the previous one.
            return False
        # The manifest's shard list is indexed BY RANK — it is either
        # complete (every rank wrote a dict shard) or empty (externally
        # written state, e.g. an orbax dir); a partial list would silently
        # remap ranks onto wrong shards.
        shards = [pending.shards.get(r) for r in range(pending.world)]
        if any(s is None for s in shards):
            shards = []
        try:
            ckpt_mod.commit_manifest(
                ckpt_dir, step=pending.step, world_size=pending.world,
                shards=shards,
                dataset_offsets=pending.offsets, arena=pending.arena)
        except FileNotFoundError:
            # The dir vanished between the acks and the commit (a restart
            # re-running an old step can race keep-K eviction of its own
            # dir). The checkpoint is gone: it must NOT become latest —
            # same outcome as a lost manifest, and just as survivable.
            return False
        self._ckpt_mgr.register(ckpt_mod.Checkpoint(ckpt_dir),
                                latest_metrics or None)
        return True

    def _poll_until_done(self, workers, history: list):
        poll_timeout = _train_knob("train_poll_timeout_s",
                                   self.run_config.poll_timeout_s)
        progress_timeout = _train_knob("train_progress_timeout_s",
                                       self.run_config.progress_timeout_s)
        latest = {}
        done = [False] * len(workers)
        pending: dict[str, _PendingCommit] = {}
        last_progress = time.monotonic()
        while not all(done):
            time.sleep(0.05)
            refs = [w.poll.remote() for w in workers]
            polls = []
            group_error = None
            for ref in refs:
                # Per-ref resolution: one dead rank must not discard the
                # SURVIVORS' drained reports for this round — their shard
                # acks may complete a commit the restart then resumes
                # from, instead of re-running work that was already done.
                try:
                    polls.append(ray_tpu.get(
                        ref, timeout=max(poll_timeout, 0.001)))
                except GetTimeoutError as e:
                    # Wedged-not-dead: the worker process answers liveness
                    # but its poll never returns (hung collective, stuck
                    # I/O). Without this deadline the run stalls for the
                    # full get timeout on EVERY poll round; with it, the
                    # FailurePolicy restarts from the committed manifest.
                    raise _WorkerGroupHung(
                        f"worker group hung: poll() exceeded "
                        f"train_poll_timeout_s={poll_timeout}s: {e}") from e
                except ray_tpu.RayTpuError as e:
                    # A hard-crashed worker (OOM kill, preempted host,
                    # os._exit) dies as an actor, not as an error report —
                    # still a worker-group failure the FailurePolicy must
                    # see, AFTER the survivors' rounds are processed.
                    polls.append(([], False, None))
                    if group_error is None:
                        group_error = _WorkerGroupError(
                            f"worker actor died: {e}")
            progressed = False
            for i, (reports, finished, err) in enumerate(polls):
                for r in reports:
                    progressed = True
                    if "error" in r:
                        raise _WorkerGroupError(
                            f"worker {i} failed:\n{r['error']}")
                    if r["rank"] == 0:
                        latest = r["metrics"]
                        history.append(r["metrics"])
                        _update_run(self, latest, len(history))
                    ack = r.get("ckpt_shard")
                    if ack:
                        ckpt_dir = ack["dir"]
                        pc = pending.get(ckpt_dir)
                        if pc is None:
                            pc = pending[ckpt_dir] = _PendingCommit(
                                ack["step"], ack["world"])
                        pc.acks.add(ack["rank"])
                        if ack.get("shard"):
                            pc.shards[ack["rank"]] = ack["shard"]
                        if ack.get("arena"):
                            pc.arena[str(ack["rank"])] = ack["arena"]
                        if ack.get("dataset_offsets"):
                            pc.offsets = ack["dataset_offsets"]
                        if self._commit_if_ready(pc, ckpt_dir, latest):
                            self._latest_committed = ckpt_dir
                            pending.pop(ckpt_dir, None)
                if err and not any("error" in r for r in reports):
                    raise _WorkerGroupError(f"worker {i} failed: {err}")
                if finished and not done[i]:
                    progressed = True
                done[i] = finished
            if group_error is not None:
                raise group_error
            now = time.monotonic()
            if progressed:
                last_progress = now
            elif (progress_timeout and progress_timeout > 0
                    and now - last_progress > progress_timeout):
                # Polls answer but NOTHING moves: no reports, no finishes.
                # The per-step progress deadline turns the wedge into a
                # FailurePolicy restart instead of an unbounded stall.
                raise _WorkerGroupHung(
                    "worker group hung: no rank reported progress for "
                    f"train_progress_timeout_s={progress_timeout}s")
        return latest


# ---- train-run registry (feeds the dashboard's Train page; parity:
# dashboard/modules/train state aggregation) ----

_TRAIN_RUNS: dict[str, dict] = {}


def _register_run(trainer):
    _TRAIN_RUNS[trainer.run_config.name] = {
        "name": trainer.run_config.name,
        "num_workers": trainer.scaling.num_workers,
        "state": "RUNNING",
        "started": time.time(),
        "iterations": 0,
        "latest_metrics": {},
    }


def _update_run(trainer, metrics: dict, iterations: int):
    run = _TRAIN_RUNS.get(trainer.run_config.name)
    if run is not None:
        run["state"] = str(trainer.state)
        run["iterations"] = iterations
        run["latest_metrics"] = {
            k: v for k, v in metrics.items()
            if isinstance(v, (int, float, str, bool))}


def _finalize_run(trainer):
    run = _TRAIN_RUNS.get(trainer.run_config.name)
    if run is not None:
        run["state"] = str(trainer.state)


def list_train_runs() -> list[dict]:
    """Dashboard/state surface: every run fit() in this driver process,
    newest first, with live state + rank-0's latest reported metrics."""
    out = []
    for run in _TRAIN_RUNS.values():
        t = dict(run)
        out.append(t)
    out.sort(key=lambda r: -r["started"])
    return out


class _WorkerGroupError(RayTpuError):
    pass


class _WorkerGroupHung(_WorkerGroupError):
    """A group declared hung by the poll/progress watchdogs — restartable
    like any group failure, but skipped for graceful-shutdown grace."""
