"""JaxTrainer: controller + worker-group actors running SPMD JAX.

Parity: reference Train v2 (`TrainController` FSM
`v2/_internal/execution/controller/controller.py:91`, worker group
`v2/.../worker_group/worker_group.py`, `FailurePolicy`
`failure_handling/failure_policy.py:14`) and the v1 `BackendExecutor`
(`train/_internal/backend_executor.py:73`).

TPU-first architecture (SURVEY §7 design stance): ONE worker actor per HOST,
not per chip — each worker owns all local TPU chips and enters the same
jit-compiled GSPMD program; multi-host meshes are formed with
jax.distributed (coordinator = worker 0). DP/FSDP/TP/SP/EP happen INSIDE the
program via shardings, so there is no NCCL-style process group to babysit:
the "backend setup" the reference does in `train/torch/config.py` reduces to
jax.distributed.initialize + mesh construction.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
import traceback
from typing import Any, Callable

import ray_tpu
from ray_tpu.core.status import RayTpuError


@dataclasses.dataclass
class ScalingConfig:
    """Parity: ray.train.ScalingConfig (air/config.py)."""

    num_workers: int = 1          # = number of hosts in the mesh
    use_tpu: bool = False
    resources_per_worker: dict | None = None
    chips_per_worker: int = 0     # 0 = all chips on the host


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    name: str = "train_run"
    storage_path: str | None = None
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    checkpoint_keep: int = 2


@dataclasses.dataclass
class Result:
    """Parity: ray.air.Result."""

    metrics: dict
    checkpoint: Any
    path: str
    error: BaseException | None = None
    metrics_history: list = dataclasses.field(default_factory=list)


class TrainWorker:
    """Actor hosting the user training loop (one per host)."""

    def __init__(self, rank: int, world_size: int, storage_dir: str,
                 coordinator: str | None, env: dict):
        os.environ.update(env)
        self.rank = rank
        self.world_size = world_size
        self.storage_dir = storage_dir
        self.coordinator = coordinator
        self._thread = None
        self._session = None

    def setup_distributed(self):
        """Join the multi-host jax runtime (no-op for world_size 1)."""
        if self.world_size > 1 and self.coordinator:
            import jax
            jax.distributed.initialize(
                coordinator_address=self.coordinator,
                num_processes=self.world_size, process_id=self.rank)
        return self.rank

    def run(self, loop_fn_bytes: bytes, loop_config: dict,
            checkpoint_path: str | None, dataset_shards: dict | None = None):
        import cloudpickle
        from ray_tpu.train import session as session_mod
        from ray_tpu.train.checkpoint import Checkpoint
        loop_fn = cloudpickle.loads(loop_fn_bytes)
        ckpt = Checkpoint(checkpoint_path) if checkpoint_path else None
        self._session = session_mod.TrainSession(
            self.rank, self.world_size, self.storage_dir, checkpoint=ckpt,
            dataset_shards=dataset_shards)
        session_mod._set_session(self._session)

        def target():
            try:
                loop_fn(loop_config)
            except BaseException as e:  # noqa: BLE001 — ship to controller
                self._session.error = e
                self._session.reports.append(
                    {"error": traceback.format_exc(), "rank": self.rank})
            finally:
                self._session.finished = True

        self._thread = threading.Thread(target=target, daemon=True)
        self._thread.start()
        return True

    def poll(self):
        """Controller heartbeat: (reports, finished, error_str)."""
        s = self._session
        if s is None:
            return [], False, None
        # Read finished BEFORE draining: the loop thread appends its final
        # report before setting finished, so this order can't lose it.
        finished = s.finished
        reports = s.drain_reports()
        err = None
        if s.error is not None:
            err = repr(s.error)
        return reports, finished, err

    def latest_checkpoint_path(self):
        s = self._session
        if s and s.latest_checkpoint:
            return s.latest_checkpoint.path
        return None

    def shutdown(self):
        return True


# controller states (parity: TrainControllerState in v2 controller.py)
INIT, RUNNING, RESTARTING, FINISHED, ERRORED = (
    "INITIALIZING", "RUNNING", "RESTARTING", "FINISHED", "ERRORED")


class JaxTrainer:
    """Parity: TorchTrainer (`train/torch/torch_trainer.py:11`) +
    DataParallelTrainer (`data_parallel_trainer.py:26`), TPU-native."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 resume_from_checkpoint=None):
        self.train_loop = train_loop_per_worker
        self.loop_config = train_loop_config or {}
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        self.state = INIT

    def _storage_dir(self) -> str:
        base = self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_train")
        path = os.path.join(base, self.run_config.name)
        os.makedirs(path, exist_ok=True)
        return path

    def _make_group(self, storage_dir: str):
        n = self.scaling.num_workers
        res = dict(self.scaling.resources_per_worker or {})
        num_tpus = res.pop("TPU", self.scaling.chips_per_worker
                           if self.scaling.use_tpu else 0)
        num_cpus = res.pop("CPU", 1)
        env = {}
        WorkerCls = ray_tpu.remote(TrainWorker).options(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=res or None)
        workers = [
            WorkerCls.remote(rank=i, world_size=n, storage_dir=storage_dir,
                             coordinator=None, env=env)
            for i in range(n)
        ]
        # Gang rendezvous (SPMD impedance, SURVEY §7 hard-part 3).
        ray_tpu.get([w.setup_distributed.remote() for w in workers],
                    timeout=300)
        return workers

    def fit(self) -> Result:
        import cloudpickle
        storage_dir = self._storage_dir()
        loop_bytes = cloudpickle.dumps(self.train_loop)
        failures_left = self.run_config.failure_config.max_failures
        resume_path = (self.resume_from_checkpoint.path
                       if self.resume_from_checkpoint else None)
        history: list[dict] = []
        latest_metrics: dict = {}
        latest_ckpt_path = resume_path

        while True:
            self.state = RUNNING
            workers = self._make_group(storage_dir)
            shards = self._split_datasets()
            ray_tpu.get([
                w.run.remote(loop_bytes, self.loop_config, latest_ckpt_path,
                             shards[i])
                for i, w in enumerate(workers)], timeout=300)
            error = None
            try:
                latest_metrics, history_part, latest_ckpt_path = (
                    self._poll_until_done(workers, latest_ckpt_path))
                history.extend(history_part)
                self.state = FINISHED
            except _WorkerGroupError as e:
                error = e
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            if error is None:
                break
            # FailurePolicy: restart the whole gang from the last checkpoint.
            if failures_left > 0:
                failures_left -= 1
                self.state = RESTARTING
                continue
            self.state = ERRORED
            from ray_tpu.train.checkpoint import Checkpoint
            return Result(metrics=latest_metrics,
                          checkpoint=Checkpoint(latest_ckpt_path)
                          if latest_ckpt_path else None,
                          path=storage_dir, error=error,
                          metrics_history=history)

        from ray_tpu.train.checkpoint import Checkpoint
        return Result(
            metrics=latest_metrics,
            checkpoint=Checkpoint(latest_ckpt_path) if latest_ckpt_path else None,
            path=storage_dir, metrics_history=history)

    def _split_datasets(self):
        """Per-worker dataset shards (parity: get_dataset_shard/streaming_split)."""
        n = self.scaling.num_workers
        shards = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "split"):
                parts = ds.split(n)
            else:
                parts = [ds] * n
            for i in range(n):
                shards[i][name] = parts[i]
        return shards

    def _poll_until_done(self, workers, latest_ckpt_path):
        history = []
        latest = {}
        done = [False] * len(workers)
        while not all(done):
            time.sleep(0.05)
            polls = ray_tpu.get(
                [w.poll.remote() for w in workers], timeout=600)
            for i, (reports, finished, err) in enumerate(polls):
                for r in reports:
                    if "error" in r:
                        raise _WorkerGroupError(
                            f"worker {i} failed:\n{r['error']}")
                    if r["rank"] == 0:
                        latest = r["metrics"]
                        history.append(r["metrics"])
                        if "checkpoint" in r:
                            latest_ckpt_path = r["checkpoint"]
                if err and not any("error" in r for r in reports):
                    raise _WorkerGroupError(f"worker {i} failed: {err}")
                done[i] = finished
        return latest, history, latest_ckpt_path


class _WorkerGroupError(RayTpuError):
    pass
