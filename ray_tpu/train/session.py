"""In-loop training session API.

Parity: reference `python/ray/train/_internal/session.py` —
`ray.train.report(:672)`, `get_checkpoint(:786)`, `get_dataset_shard(:1114)`.
The session lives inside each training worker actor; report() hands metrics
(+ optional checkpoint data) to the worker's mailbox, which the controller
polls.
"""

from __future__ import annotations

import threading
from typing import Any


class TrainSession:
    def __init__(self, rank: int, world_size: int, storage_dir: str,
                 checkpoint=None, dataset_shards: dict | None = None,
                 local_rank: int = 0, local_world_size: int = 1):
        self.rank = rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.storage_dir = storage_dir
        self.resume_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.reports: list[dict] = []
        self.latest_checkpoint = None
        self.finished = False
        self.error: BaseException | None = None
        self._lock = threading.Lock()

    def report(self, metrics: dict, checkpoint=None):
        with self._lock:
            entry = {"metrics": dict(metrics), "rank": self.rank}
            if checkpoint is not None and self.rank == 0:
                from ray_tpu.train.checkpoint import Checkpoint
                if not isinstance(checkpoint, Checkpoint):
                    checkpoint = Checkpoint.from_dict(
                        checkpoint, self.storage_dir,
                        step=metrics.get("step", len(self.reports)))
                self.latest_checkpoint = checkpoint
                entry["checkpoint"] = checkpoint.path
            self.reports.append(entry)

    def drain_reports(self) -> list[dict]:
        with self._lock:
            out = self.reports
            self.reports = []
            return out


_session: TrainSession | None = None


def _set_session(s: TrainSession | None):
    global _session
    _session = s


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError("not inside a ray_tpu.train training loop")
    return _session


def report(metrics: dict, checkpoint=None):
    get_session().report(metrics, checkpoint)


def get_checkpoint():
    return get_session().resume_checkpoint


def get_dataset_shard(name: str = "train"):
    return get_session().dataset_shards.get(name)


def get_world_rank() -> int:
    return get_session().rank


def get_world_size() -> int:
    return get_session().world_size


class TrainContext:
    """Parity: ray.train.get_context() (TrainContext in the reference) —
    a read-only view over the worker's session."""

    def get_world_rank(self) -> int:
        return get_world_rank()

    def get_world_size(self) -> int:
        return get_world_size()

    def get_local_rank(self) -> int:
        return get_session().local_rank

    def get_local_world_size(self) -> int:
        return get_session().local_world_size

    def get_trial_dir(self) -> str:
        return get_session().storage_dir


def get_context() -> TrainContext:
    get_session()  # raises outside a training loop
    return TrainContext()
