"""In-loop training session API.

Parity: reference `python/ray/train/_internal/session.py` —
`ray.train.report(:672)`, `get_checkpoint(:786)`, `get_dataset_shard(:1114)`.
The session lives inside each training worker actor; report() hands metrics
(+ optional checkpoint data) to the worker's mailbox, which the controller
polls.

Checkpoint reports are phase 1 of the two-phase commit (train/checkpoint.py):
EVERY rank that passes `checkpoint=` durably writes its own shard into the
step's deterministic directory (tmp+fsync+rename) and acks the write in its
report; the controller commits the manifest only once all ranks of the step
acked. A rank that dies between the shard write and the ack (the
`train.ckpt_shard_abandon` chaos site) leaves an uncommitted directory the
next restart garbage-collects — never a torn checkpoint that looks
resumable.
"""

from __future__ import annotations

import threading
from typing import Any

from ray_tpu.core import chaos


class TrainSession:
    def __init__(self, rank: int, world_size: int, storage_dir: str,
                 checkpoint=None, dataset_shards: dict | None = None,
                 local_rank: int = 0, local_world_size: int = 1,
                 dataset_offsets: dict | None = None):
        self.rank = rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.storage_dir = storage_dir
        self.resume_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        # name -> rows the run consumed BEFORE this (re)start (recorded in
        # the committed manifest; the trainer already re-split the shards
        # past them — exposed so loops can keep their own cursors honest).
        self.dataset_offsets = dict(dataset_offsets or {})
        self.reports: list[dict] = []
        self._ckpt_seq = 0  # fallback step for loops that don't report one
        self.latest_checkpoint = None
        self.finished = False
        self.error: BaseException | None = None
        self._lock = threading.Lock()

    def report(self, metrics: dict, checkpoint=None,
               dataset_offsets: dict | None = None):
        # Mid-step crash probe: fires BEFORE the shard write, so the step's
        # report (and any checkpoint ack) is lost exactly the way a
        # preempted host loses it.
        chaos.kill("train.worker_kill")
        entry = {"metrics": dict(metrics), "rank": self.rank}
        if checkpoint is not None:
            entry.update(self._write_ckpt_shard(
                checkpoint, metrics, dataset_offsets))
        with self._lock:
            self.reports.append(entry)

    def _write_ckpt_shard(self, checkpoint, metrics: dict,
                          dataset_offsets: dict | None) -> dict:
        """Phase 1: durably persist this rank's shard and build the ack.
        Returns report fields ({} when the rank abandons pre-ack)."""
        from ray_tpu.train import checkpoint as ckpt_mod
        # Monotonic fallback: reports are DRAINED by the controller's
        # polls, so len(reports) repeats and would collide step dirs.
        step = int(metrics.get("step", self._ckpt_seq))
        self._ckpt_seq += 1
        if isinstance(checkpoint, ckpt_mod.Checkpoint):
            # Externally-written state (e.g. an orbax save_state dir the
            # loop owns): nothing to write, but the commit protocol still
            # gates on every rank acking it reached this point.
            ckpt_dir, shard = checkpoint.path, None
        else:
            ckpt_dir = ckpt_mod.step_dir(self.storage_dir, step)
            shard = ckpt_mod.write_shard(
                checkpoint, ckpt_dir, self.rank, self.world_size)
        # The crash window between durability and the ack: the shard file
        # exists, the controller never hears — the manifest must not
        # commit, and restart must fall back to the previous step.
        if chaos.site("train.ckpt_shard_abandon"):
            return {}
        arena_hex = None
        if shard is not None:
            arena_hex = self._seal_shard_arena(checkpoint)
        self.latest_checkpoint = ckpt_mod.Checkpoint(ckpt_dir)
        ack = {"dir": ckpt_dir, "step": step, "rank": self.rank,
               "world": self.world_size, "shard": shard}
        if arena_hex:
            ack["arena"] = arena_hex
        if dataset_offsets and self.rank == 0:
            ack["dataset_offsets"] = dict(dataset_offsets)
        return {"ckpt_shard": ack}

    def _seal_shard_arena(self, data) -> str | None:
        """Seal the shard as a tagged arena object so a restarted gang can
        restore it over objxfer from a surviving peer instead of shared
        disk. Best-effort: no runtime / store pressure never blocks the
        report (the committed disk shard is the source of truth)."""
        try:
            from ray_tpu.core.config import get_config
            from ray_tpu.core.runtime import current_runtime
            rt = current_runtime()
            if rt is None or not get_config().train_ckpt_arena:
                return None
            put = getattr(rt, "put_tagged", None) or rt.put
            return put(data).hex()
        except Exception:  # noqa: BLE001 — acceleration only, never gates
            return None

    def drain_reports(self) -> list[dict]:
        with self._lock:
            out = self.reports
            self.reports = []
            return out


_session: TrainSession | None = None


def _set_session(s: TrainSession | None):
    global _session
    _session = s


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError("not inside a ray_tpu.train training loop")
    return _session


def report(metrics: dict, checkpoint=None, dataset_offsets: dict | None = None):
    get_session().report(metrics, checkpoint,
                         dataset_offsets=dataset_offsets)


def get_checkpoint():
    return get_session().resume_checkpoint


def get_dataset_shard(name: str = "train"):
    return get_session().dataset_shards.get(name)


def get_dataset_offset(name: str = "train") -> int:
    """Rows of `name` consumed before this (re)start (already skipped in
    the shard this rank received)."""
    return int(get_session().dataset_offsets.get(name, 0))


def get_world_rank() -> int:
    return get_session().rank


def get_world_size() -> int:
    return get_session().world_size


class TrainContext:
    """Parity: ray.train.get_context() (TrainContext in the reference) —
    a read-only view over the worker's session."""

    def get_world_rank(self) -> int:
        return get_world_rank()

    def get_world_size(self) -> int:
        return get_world_size()

    def get_local_rank(self) -> int:
        return get_session().local_rank

    def get_local_world_size(self) -> int:
        return get_session().local_world_size

    def get_trial_dir(self) -> str:
        return get_session().storage_dir


def get_context() -> TrainContext:
    get_session()  # raises outside a training loop
    return TrainContext()
