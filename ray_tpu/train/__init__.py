"""Distributed training library (JaxTrainer).

Parity: reference `python/ray/train/` (v2 architecture: controller FSM +
worker group, `v2/_internal/execution/controller/controller.py:91`) — but the
backend is GSPMD over a device mesh instead of torch DDP process groups:
DP/FSDP/TP/SP/EP are sharding configs lowered by XLA, not collective calls.
"""

from ray_tpu.train.step import TrainState, make_train_step
from ray_tpu.train.backend import Backend, JaxDistributedConfig
from ray_tpu.train.trainer import (JaxTrainer, ScalingConfig, RunConfig,
                                   list_train_runs)
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train import session
from ray_tpu.train.session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)

__all__ = ["JaxTrainer", "ScalingConfig", "RunConfig", "TrainState",
           "make_train_step", "Checkpoint", "CheckpointManager", "session",
           "report", "get_checkpoint", "get_context", "get_dataset_shard",
           "Backend", "JaxDistributedConfig"]
