"""Framework backends for the train worker group.

Parity: reference `train/_internal/backend_executor.py:73` driving
`Backend.on_start` hooks (`train/backend.py` in the reference; torch's
`train/torch/config.py` runs `dist.init_process_group`). The JAX path needs
no backend object — multi-host SPMD setup is `jax.distributed.initialize`,
done inline by the worker — so Backend exists for the frameworks that DO
carry process-group state (torch today; anything gloo/mpi-shaped tomorrow).
"""

from __future__ import annotations


class Backend:
    """Worker-group framework hooks, executed inside each worker actor."""

    #: whether _make_group must mint a rendezvous address for the gang
    needs_coordinator: bool = False

    def on_worker_start(self, rank: int, world_size: int,
                        coordinator: str | None):
        """Called on every worker before the user loop starts."""

    def on_worker_shutdown(self):
        """Called when the worker group is torn down (best effort)."""


class JaxDistributedConfig(Backend):
    """Cross-host SPMD gang: every worker joins one jax runtime via
    `jax.distributed.initialize` (coordinator = rank 0's node), so the
    workers' local devices form a single global mesh. Pass as
    `JaxTrainer(..., jax_config=JaxDistributedConfig())` for multi-host
    runs; without it workers run independent single-host jax (data-parallel
    via the host collective layer)."""

    needs_coordinator = True

    def __init__(self, *, local_device_ids=None):
        self.local_device_ids = local_device_ids

    def on_worker_start(self, rank: int, world_size: int,
                        coordinator: str | None):
        if world_size <= 1 or coordinator is None:
            return
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=world_size, process_id=rank,
            local_device_ids=self.local_device_ids)

    def on_worker_shutdown(self):
        import jax
        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001 — already down
            pass
