"""Sharded train step construction (the GSPMD lowering).

The scaling-book recipe in code: put params+optimizer state in sharded
TrainState, jit the step with NamedShardings derived from the logical-axis
rules, and let XLA insert the gradient psums / FSDP all-gathers on ICI.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import ShardingRules, declared_param_specs


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def make_train_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                    mesh: Mesh, param_axes, rules: ShardingRules | None = None,
                    batch_spec: P | None = None, donate: bool = True):
    """Returns (init_fn, step_fn, state_shardings).

    loss_fn(params, batch) -> scalar. param_axes: logical-axis pytree matching
    params. Both fns are jit-compiled with explicit in/out shardings so the
    same code runs 1-chip or N-chip.
    """
    rules = rules or ShardingRules.default()
    # The declared table (parallel/sharding.py): graphcheck cross-checks
    # the lowered step against the same source, so in_shardings here can
    # never silently diverge from the declaration.
    param_specs = declared_param_specs(param_axes, rules)
    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs)
    batch_spec = batch_spec if batch_spec is not None else P(("dp", "fsdp"))
    batch_sharding = NamedSharding(mesh, batch_spec)
    repl = NamedSharding(mesh, P())

    def init_fn(params):
        opt_state = optimizer.init(params)
        return TrainState(params=params, opt_state=opt_state,
                          step=jnp.zeros((), jnp.int32))

    # Optimizer state mirrors param sharding: optax moment trees (adam mu/nu,
    # momentum trace, ...) have the params' tree STRUCTURE, so substitute the
    # param shardings wholesale at any matching subtree. Shape-based matching
    # would mis-assign when differently-sharded params share a shape (e.g.
    # wq P(None,'fsdp','tp') vs wo P(None,'tp','fsdp'), both (L,d,d)).
    def opt_shardings(opt_state, params):
        param_treedef = jax.tree.structure(params)
        if param_treedef.num_leaves <= 1:
            # Degenerate single-leaf params: every leaf "matches" the
            # structure, so fall back to shape matching (no ambiguity with
            # one param) to avoid sharding adam's scalar count.
            p_shape = getattr(jax.tree.leaves(params)[0], "shape", None)
            p_shard = jax.tree.leaves(param_shardings)[0]
            return jax.tree.map(
                lambda leaf: p_shard
                if getattr(leaf, "shape", None) == p_shape else repl,
                opt_state)

        def is_param_tree(node):
            return jax.tree.structure(node) == param_treedef

        return jax.tree.map(
            lambda sub: param_shardings if is_param_tree(sub) else repl,
            opt_state, is_leaf=is_param_tree)

    def step_fn(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        return TrainState(new_params, new_opt, state.step + 1), loss

    def state_shardings(state: TrainState) -> TrainState:
        """The full TrainState sharding tree for THIS mesh — the abstract
        restore target of the elastic re-mesh path: feed it through
        `checkpoint.abstract_state` and orbax assembles an N-way save
        directly into this mesh's layout (no gather, no host blowup)."""
        return TrainState(
            params=param_shardings,
            opt_state=opt_shardings(state.opt_state, state.params),
            step=repl)

    def compile_for(state: TrainState, sample_batch):
        if mesh.devices.size == 1:
            # Single-chip: every NamedSharding is the trivial one, so skip the
            # annotations entirely. Semantically identical, and measurably
            # faster on backends where sharded executables take a slower
            # dispatch path (the axon-tunneled chip round-trips buffers per
            # call when in/out shardings are present: ~25x step-time blowup).
            return jax.jit(step_fn, donate_argnums=(0,) if donate else ())
        shardings = state_shardings(state)
        batch_shardings = jax.tree.map(lambda _: batch_sharding, sample_batch)
        return jax.jit(
            step_fn,
            in_shardings=(shardings, batch_shardings),
            out_shardings=(shardings, repl),
            donate_argnums=(0,) if donate else ())

    # Attached rather than returned: the 4-tuple is a public surface.
    compile_for.state_shardings = state_shardings
    return init_fn, step_fn, compile_for, param_shardings


def __graphcheck__(gc):
    """graphcheck hook (tools/graphcheck): the sharded train step, lowered
    through the REAL compile_for wrapper on a simulated dp2 x fsdp2 mesh.
    Pins: state donated (params + opt moments aliased into the outputs),
    FSDP params never lower replicated, lowered in-shardings match the
    declared parallel/sharding.py table, and the collective counts of the
    FSDP gather/psum pattern."""

    def build(mesh):
        d, f, b = 256, 512, 32
        param_axes = {"w_in": ("embed", "mlp"), "w_out": ("mlp", "embed")}

        def loss_fn(params, batch):
            h = jnp.tanh(batch["x"] @ params["w_in"])
            y = h @ params["w_out"]
            return jnp.mean((y - batch["y"]) ** 2)

        init_fn, step_fn, compile_for, _ = make_train_step(
            loss_fn, optax.adam(1e-3), mesh, param_axes)
        params = {
            "w_in": jax.ShapeDtypeStruct((d, f), jnp.float32),
            "w_out": jax.ShapeDtypeStruct((f, d), jnp.float32)}
        state = jax.eval_shape(init_fn, params)
        batch = {"x": jax.ShapeDtypeStruct((b, d), jnp.float32),
                 "y": jax.ShapeDtypeStruct((b, d), jnp.float32)}
        specs = declared_param_specs(param_axes)
        return gc.GraphSpec(
            name="train.step", fn=step_fn, args=(state, batch),
            jit_fn=compile_for(state, batch), donate_argnums=(0,),
            declared_in_specs=tuple(
                (f"'{k}'", s) for k, s in sorted(specs.items())),
            expect_sharded=("w_in", "w_out"),
            min_donate_bytes=1 << 16, arg_names=("state", "batch"))

    # tp rides along at size 1: the declared rules map "mlp" -> "tp", so
    # the mesh must carry the axis name even when it is not being tested.
    gc.register("train.step", build,
                meshes=({"dp": 2, "fsdp": 2, "tp": 1},))
