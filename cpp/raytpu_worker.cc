// Cross-language worker runtime: a standalone C++ process that registers
// with a node agent, receives task-dispatch frames, executes registered
// native functions, and returns results — no Python and NO PICKLE anywhere
// on its path (parity: the reference's C++ worker runtime,
// cpp/src/ray/runtime/task/task_executor.cc + core_worker.proto:457).
//
// Plumbing:
//   argv: <store_path> <worker_id_hex> <fd>
//   - maps the node's shared-memory arena (the SAME file every Python
//     process on the node maps) and calls the store's C API directly —
//     object_store.cpp is compiled into this binary;
//   - speaks length-prefixed protobuf WorkerFrame frames on the inherited
//     socket fd (outer framing identical to transport.py, proto flag
//     REQUIRED — a pickle frame is a loud protocol error, which is this
//     worker's half of the no-pickle plane assertion);
//   - task args arrive as a tagged raytpu.TaskArgs payload; object_id
//     args are read zero-copy out of the arena (tagged-object layout,
//     object_store.py TAGGED_META); returns are sealed back into the
//     arena in the same layout and reported as arena ids.
//
// Functions are addressed by REGISTERED SYMBOL NAME (spec.name). The
// built-in registry below covers the e2e tests and the bench; real
// deployments extend it (or swap in a dlopen-based resolver) by editing
// this table — the build is one cached g++ invocation away
// (_native/build.py build_binary), so there is no build-system step.

#include <time.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <functional>
#include <map>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <vector>

#include "pb/raytpu.pb.h"

// ---- shared-memory store C API (object_store.cpp, linked in) ----
extern "C" {
int store_validate(void* base);
int store_create(void* base, const uint8_t* id, uint64_t data_size,
                 uint64_t meta_size, uint64_t* out_offset);
int store_seal(void* base, const uint8_t* id);
int store_get(void* base, const uint8_t* id, uint64_t* out_offset,
              uint64_t* out_data_size, uint64_t* out_meta_size);
int store_release(void* base, const uint8_t* id);
}

namespace {

constexpr uint32_t kProtoFlag = 0x80000000u;
constexpr char kTaggedMeta[] = "rtv1";  // object_store.py TAGGED_META

double WallClock() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return static_cast<double>(ts.tv_sec) + ts.tv_nsec * 1e-9;
}

bool SendAll(int fd, const char* data, size_t n) {
  while (n) {
    ssize_t w = ::write(fd, data, n);
    if (w <= 0) return false;
    data += w;
    n -= w;
  }
  return true;
}

bool RecvAll(int fd, char* data, size_t n) {
  while (n) {
    ssize_t r = ::read(fd, data, n);
    if (r <= 0) return false;
    data += r;
    n -= r;
  }
  return true;
}

bool SendFrame(int fd, const std::string& payload) {
  char hdr[12];
  uint64_t len = payload.size();
  uint32_t nbufs = kProtoFlag;
  memcpy(hdr, &len, 8);
  memcpy(hdr + 8, &nbufs, 4);
  return SendAll(fd, hdr, 12) && SendAll(fd, payload.data(), payload.size());
}

// A task argument resolved for execution: format + a borrowed byte span.
// Arena args point STRAIGHT into the mmapped store (zero-copy; released
// after the reply), inline args into the parsed frame.
struct ArgView {
  std::string format;
  const char* data = nullptr;
  size_t size = 0;

  int64_t AsI64() const {
    int64_t v = 0;
    if (format == "i64" && size == 8) memcpy(&v, data, 8);
    return v;
  }
  double AsF64() const {
    double v = 0;
    if (format == "f64" && size == 8) memcpy(&v, data, 8);
    return v;
  }
  std::string Str() const { return std::string(data, size); }
};

raytpu::Value I64(int64_t v) {
  raytpu::Value out;
  out.set_format("i64");
  out.set_data(&v, 8);
  return out;
}
raytpu::Value F64(double v) {
  raytpu::Value out;
  out.set_format("f64");
  out.set_data(&v, 8);
  return out;
}
raytpu::Value Utf8(const std::string& s) {
  raytpu::Value out;
  out.set_format("utf8");
  out.set_data(s);
  return out;
}

using TaskFn = std::function<bool(const std::vector<ArgView>&,
                                  std::vector<raytpu::Value>*,
                                  std::string*)>;

// ---- the native symbol registry (spec.name -> function) ----
std::map<std::string, TaskFn> BuildRegistry() {
  std::map<std::string, TaskFn> reg;
  reg["rt.noop"] = [](const std::vector<ArgView>&,
                      std::vector<raytpu::Value>* out, std::string*) {
    out->push_back(I64(0));
    return true;
  };
  reg["rt.pid"] = [](const std::vector<ArgView>&,
                     std::vector<raytpu::Value>* out, std::string*) {
    out->push_back(I64(static_cast<int64_t>(getpid())));
    return true;
  };
  reg["rt.add_i64"] = [](const std::vector<ArgView>& args,
                         std::vector<raytpu::Value>* out, std::string*) {
    int64_t acc = 0;
    for (const auto& a : args) acc += a.AsI64();
    out->push_back(I64(acc));
    return true;
  };
  reg["rt.mul_f64"] = [](const std::vector<ArgView>& args,
                         std::vector<raytpu::Value>* out, std::string*) {
    double acc = 1.0;
    for (const auto& a : args) acc *= a.AsF64();
    out->push_back(F64(acc));
    return true;
  };
  reg["rt.concat_utf8"] = [](const std::vector<ArgView>& args,
                             std::vector<raytpu::Value>* out, std::string*) {
    std::string s;
    for (const auto& a : args) s += a.Str();
    out->push_back(Utf8(s));
    return true;
  };
  // Byte length of any arg — works on arena args without copying them.
  reg["rt.len"] = [](const std::vector<ArgView>& args,
                     std::vector<raytpu::Value>* out, std::string* err) {
    if (args.empty()) {
      *err = "rt.len needs one argument";
      return false;
    }
    out->push_back(I64(static_cast<int64_t>(args[0].size)));
    return true;
  };
  // Sum of the raw bytes of arg 0 — touches every byte of a (possibly
  // shm-arena) payload zero-copy; the e2e test checks the exact sum.
  reg["rt.sum_bytes"] = [](const std::vector<ArgView>& args,
                           std::vector<raytpu::Value>* out,
                           std::string* err) {
    if (args.empty()) {
      *err = "rt.sum_bytes needs one argument";
      return false;
    }
    int64_t acc = 0;
    const uint8_t* p = reinterpret_cast<const uint8_t*>(args[0].data);
    for (size_t i = 0; i < args[0].size; i++) acc += p[i];
    out->push_back(I64(acc));
    return true;
  };
  // Echo every arg back (exercises multi-return: num_returns == nargs).
  reg["rt.echo"] = [](const std::vector<ArgView>& args,
                      std::vector<raytpu::Value>* out, std::string*) {
    for (const auto& a : args) {
      raytpu::Value v;
      v.set_format(a.format);
      v.set_data(a.data, a.size);
      out->push_back(v);
    }
    return true;
  };
  reg["rt.sleep_ms"] = [](const std::vector<ArgView>& args,
                          std::vector<raytpu::Value>* out, std::string*) {
    int64_t ms = args.empty() ? 0 : args[0].AsI64();
    struct timespec ts = {ms / 1000, (ms % 1000) * 1000000L};
    nanosleep(&ts, nullptr);
    out->push_back(I64(ms));
    return true;
  };
  reg["rt.fail"] = [](const std::vector<ArgView>&,
                      std::vector<raytpu::Value>*, std::string* err) {
    *err = "rt.fail raised (intentional cross-language task failure)";
    return false;
  };
  return reg;
}

struct Worker {
  int fd;
  void* base = nullptr;
  std::string worker_id;
  std::map<std::string, TaskFn> registry = BuildRegistry();

  bool SealTagged(const std::string& oid, const raytpu::Value& v) {
    uint32_t fmt_len = static_cast<uint32_t>(v.format().size());
    uint64_t total = 4 + fmt_len + v.data().size();
    uint64_t off = 0;
    int rc = store_create(base, reinterpret_cast<const uint8_t*>(oid.data()),
                          total, 4, &off);
    if (rc == -3 /* ERR_EXISTS */) return true;  // a prior attempt sealed it
    if (rc != 0) return false;
    char* dst = static_cast<char*>(base) + off;
    memcpy(dst, &fmt_len, 4);
    memcpy(dst + 4, v.format().data(), fmt_len);
    memcpy(dst + 4 + fmt_len, v.data().data(), v.data().size());
    memcpy(dst + total, kTaggedMeta, 4);  // meta region follows the data
    return store_seal(base,
                      reinterpret_cast<const uint8_t*>(oid.data())) == 0;
  }

  // Resolve one Arg; arena refs fill `held` for post-exec release.
  bool ResolveArg(const raytpu::Arg& a, std::vector<ArgView>* out,
                  std::vector<std::string>* held, std::string* err) {
    if (a.has_object_id()) {
      const auto& oid = a.object_id();
      uint64_t off = 0, dsz = 0, msz = 0;
      // Poll briefly: the agent stages deps before dispatch, so a miss
      // here is a race with a concurrent seal, not a missing transfer.
      int rc = -1;
      for (int i = 0; i < 2000; i++) {
        rc = store_get(base, reinterpret_cast<const uint8_t*>(oid.data()),
                       &off, &dsz, &msz);
        if (rc == 0) break;
        struct timespec ts = {0, 5 * 1000000L};  // 5ms
        nanosleep(&ts, nullptr);
      }
      if (rc != 0) {
        *err = "arena object missing for arg (never staged?)";
        return false;
      }
      const char* data = static_cast<const char*>(base) + off;
      if (msz != 4 || memcmp(data + dsz, kTaggedMeta, 4) != 0) {
        store_release(base, reinterpret_cast<const uint8_t*>(oid.data()));
        *err = "arena arg is not a tagged object (pickle payload on the "
               "no-pickle plane)";
        return false;
      }
      uint32_t fmt_len = 0;
      memcpy(&fmt_len, data, 4);
      if (4 + static_cast<uint64_t>(fmt_len) > dsz) {
        store_release(base, reinterpret_cast<const uint8_t*>(oid.data()));
        *err = "corrupt tagged arena object";
        return false;
      }
      held->push_back(oid);
      ArgView v;
      v.format.assign(data + 4, fmt_len);
      v.data = data + 4 + fmt_len;
      v.size = dsz - 4 - fmt_len;
      if (v.format == "pickle") {
        *err = "pickle-format arena arg on the no-pickle plane";
        return false;
      }
      out->push_back(std::move(v));
      return true;
    }
    const raytpu::Value& val = a.value();
    if (val.format() == "pickle") {
      *err = "pickle-format Value arg on the no-pickle plane";
      return false;
    }
    ArgView v;
    v.format = val.format();
    v.data = val.data().data();
    v.size = val.data().size();
    out->push_back(std::move(v));
    return true;
  }

  void Execute(const raytpu::TaskSpec& spec) {
    raytpu::WorkerDone done;
    done.task_id = spec.task_id;
    done.attempt = spec.max_retries - spec.retries_left;
    done.exec_start = WallClock();
    std::string err;
    std::vector<raytpu::Value> results;
    std::vector<std::string> held;
    raytpu::TaskArgs targs;
    if (spec.payload.format() != "task_args") {
      err = "dispatch payload is not a tagged TaskArgs (no-pickle plane "
            "violation)";
    } else {
      targs.Parse(
          reinterpret_cast<const uint8_t*>(spec.payload.data().data()),
          spec.payload.data().size());
      std::vector<ArgView> args;
      bool ok = true;
      for (const auto& a : targs.args) {
        if (!ResolveArg(a, &args, &held, &err)) {
          ok = false;
          break;
        }
      }
      done.args_ready = WallClock();
      if (ok) {
        auto it = registry.find(spec.name);
        if (it == registry.end()) {
          err = "no native symbol registered for '" + spec.name + "'";
        } else if (it->second(args, &results, &err)) {
          if (results.size() != spec.return_ids.size()) {
            err = "task returned " + std::to_string(results.size()) +
                  " values, expected " +
                  std::to_string(spec.return_ids.size());
            results.clear();
          }
        }
      }
    }
    done.exec_done = WallClock();
    for (size_t i = 0; i < spec.return_ids.size(); i++) {
      raytpu::WorkerOut o;
      o.object_id = spec.return_ids[i];
      if (!err.empty()) {
        o.status = "err";
        o.has_error = true;
        o.error = Utf8(err);
      } else if (SealTagged(spec.return_ids[i], results[i])) {
        o.status = "shm";
      } else {
        o.status = "err";
        o.has_error = true;
        o.error = Utf8("failed to seal return into the arena");
      }
      done.outs.push_back(std::move(o));
    }
    for (const auto& oid : held)
      store_release(base, reinterpret_cast<const uint8_t*>(oid.data()));
    done.seal = WallClock();
    SendFrame(fd, raytpu::WorkerFrame::SerializeDone(done));
  }

  int Run() {
    // Announce: worker id + pid + the registered symbol table.
    raytpu::WorkerFrame hello;
    hello.hello.worker_id = worker_id;
    hello.hello.pid = getpid();
    hello.hello.language = "cpp";
    for (const auto& kv : registry) hello.hello.symbols.push_back(kv.first);
    if (!SendFrame(fd, hello.SerializeHello())) return 1;

    char hdr[12];
    std::string payload;
    while (RecvAll(fd, hdr, 12)) {
      uint64_t len = 0;
      uint32_t nbufs = 0;
      memcpy(&len, hdr, 8);
      memcpy(&nbufs, hdr + 8, 4);
      if (!(nbufs & kProtoFlag)) {
        // The no-pickle assertion, enforced at the reader: this worker
        // cannot and will not decode a pickle frame.
        fprintf(stderr,
                "raytpu_worker: non-protobuf frame on the worker channel "
                "(nbufs=0x%x) — no-pickle plane violation\n", nbufs);
        return 3;
      }
      payload.resize(len);
      if (len && !RecvAll(fd, payload.data(), len)) break;
      raytpu::WorkerFrame frame;
      if (!frame.Parse(reinterpret_cast<const uint8_t*>(payload.data()),
                       payload.size())) {
        fprintf(stderr, "raytpu_worker: unparseable WorkerFrame\n");
        return 3;
      }
      if (frame.which == raytpu::WorkerFrame::kShutdown) return 0;
      if (frame.which == raytpu::WorkerFrame::kExec) Execute(frame.exec_spec);
    }
    return 0;  // agent hung up: clean exit
  }
};

}  // namespace

int main(int argc, char** argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <store_path> <worker_id_hex> <fd>\n", argv[0]);
    return 2;
  }
  Worker w;
  // worker_id arrives hex-encoded; the wire carries raw bytes.
  const char* hex = argv[2];
  for (size_t i = 0; hex[i] && hex[i + 1]; i += 2) {
    auto nyb = [](char c) -> int {
      if (c >= '0' && c <= '9') return c - '0';
      if (c >= 'a' && c <= 'f') return c - 'a' + 10;
      if (c >= 'A' && c <= 'F') return c - 'A' + 10;
      return 0;
    };
    w.worker_id.push_back(static_cast<char>((nyb(hex[i]) << 4)
                                            | nyb(hex[i + 1])));
  }
  w.fd = atoi(argv[3]);

  int sfd = open(argv[1], O_RDWR);
  if (sfd < 0) {
    fprintf(stderr, "raytpu_worker: cannot open store %s\n", argv[1]);
    return 2;
  }
  struct stat st;
  fstat(sfd, &st);
  w.base = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                sfd, 0);
  close(sfd);
  if (w.base == MAP_FAILED || store_validate(w.base) != 0) {
    fprintf(stderr, "raytpu_worker: store mmap/validate failed\n");
    return 2;
  }
  return w.Run();
}
