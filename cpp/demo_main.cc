// Demo / test driver: init, put/get, cross-language task submission.
// Prints assertions the test harness checks.
#include <cstdio>
#include <cstring>

#include "raytpu_client.h"

int main(int argc, char** argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: %s <host> <port>\n", argv[0]);
    return 2;
  }
  raytpu_client::Client c;
  if (!c.Connect(argv[1], atoi(argv[2]))) {
    fprintf(stderr, "connect: %s\n", c.error().c_str());
    return 1;
  }
  printf("INIT cpus=%.0f\n", c.cluster_resources().at("CPU"));

  std::string oid = c.PutRaw("hello-from-cpp");
  bool found = false;
  raytpu::Value v = c.Get(oid, 30, &found);
  if (!found || v.data() != "hello-from-cpp") {
    fprintf(stderr, "put/get mismatch\n");
    return 1;
  }
  printf("PUTGET ok\n");

  auto rids = c.Submit("math.hypot", {raytpu_client::Client::F64(3.0),
                                      raytpu_client::Client::F64(4.0)});
  if (rids.empty()) {
    fprintf(stderr, "submit: %s\n", c.error().c_str());
    return 1;
  }
  v = c.Get(rids[0], 60, &found);
  double out = 0;
  if (!found || v.format() != "f64" || v.data().size() != 8) {
    fprintf(stderr, "bad task result\n");
    return 1;
  }
  memcpy(&out, v.data().data(), 8);
  printf("TASK math.hypot(3,4)=%.1f\n", out);

  // An object put here feeds a task by reference: string upper-cased by a
  // Python worker.
  rids = c.Submit("builtins.len", {raytpu_client::Client::Utf8("12345")});
  v = c.Get(rids[0], 60, &found);
  int64_t n = 0;
  memcpy(&n, v.data().data(), 8);
  printf("TASK len=%lld\n", (long long)n);

  if (!c.KvPut("cpp-key", "cpp-val")) return 1;
  std::string got;
  if (!c.KvGet("cpp-key", &got) || got != "cpp-val") return 1;
  printf("KV ok\n");
  printf("ALL OK\n");
  return 0;
}
