// Demo / test driver: init, put/get, cross-language task submission.
// Prints assertions the test harness checks.
#include <cstdio>
#include <cstring>

#include "raytpu_client.h"

int main(int argc, char** argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: %s <host> <port>\n", argv[0]);
    return 2;
  }
  raytpu_client::Client c;
  if (!c.Connect(argv[1], atoi(argv[2]))) {
    fprintf(stderr, "connect: %s\n", c.error().c_str());
    return 1;
  }
  printf("INIT cpus=%.0f\n", c.cluster_resources().at("CPU"));

  std::string oid = c.PutRaw("hello-from-cpp");
  bool found = false;
  raytpu::Value v = c.Get(oid, 30, &found);
  if (!found || v.data() != "hello-from-cpp") {
    fprintf(stderr, "put/get mismatch\n");
    return 1;
  }
  printf("PUTGET ok\n");

  auto rids = c.Submit("math.hypot", {raytpu_client::Client::F64(3.0),
                                      raytpu_client::Client::F64(4.0)});
  if (rids.empty()) {
    fprintf(stderr, "submit: %s\n", c.error().c_str());
    return 1;
  }
  v = c.Get(rids[0], 60, &found);
  double out = 0;
  if (!found || v.format() != "f64" || v.data().size() != 8) {
    fprintf(stderr, "bad task result\n");
    return 1;
  }
  memcpy(&out, v.data().data(), 8);
  printf("TASK math.hypot(3,4)=%.1f\n", out);

  // An object put here feeds a task by reference: string upper-cased by a
  // Python worker.
  rids = c.Submit("builtins.len", {raytpu_client::Client::Utf8("12345")});
  v = c.Get(rids[0], 60, &found);
  int64_t n = 0;
  memcpy(&n, v.data().data(), 8);
  printf("TASK len=%lld\n", (long long)n);

  if (!c.KvPut("cpp-key", "cpp-val")) return 1;
  std::string got;
  if (!c.KvGet("cpp-key", &got) || got != "cpp-val") return 1;
  printf("KV ok\n");

  // Actor lifecycle, no Python on this side: create a Python actor by
  // importable class name, call it (ordered), wait, read results, kill.
  std::string aid = c.CreateActor("tests.xlang_helpers.CppCounter",
                                  {raytpu_client::Client::I64(10)});
  if (aid.empty()) {
    fprintf(stderr, "create_actor: %s\n", c.error().c_str());
    return 1;
  }
  std::string r1 = c.CallActor(aid, "add", {raytpu_client::Client::I64(5)});
  std::string r2 = c.CallActor(aid, "add", {raytpu_client::Client::I64(7)});
  std::string r3 = c.CallActor(aid, "total", {});
  if (r1.empty() || r2.empty() || r3.empty()) {
    fprintf(stderr, "actor_call: %s\n", c.error().c_str());
    return 1;
  }
  std::vector<std::string> ready;
  if (!c.Wait({r1, r2, r3}, 3, 60, &ready) || ready.size() != 3) {
    fprintf(stderr, "wait: %s\n", c.error().c_str());
    return 1;
  }
  int64_t v1 = 0, v2 = 0, v3 = 0;
  v = c.Get(r1, 60, &found);
  if (!found || v.format() != "i64") return 1;
  memcpy(&v1, v.data().data(), 8);
  v = c.Get(r2, 60, &found);
  memcpy(&v2, v.data().data(), 8);
  v = c.Get(r3, 60, &found);
  memcpy(&v3, v.data().data(), 8);
  if (v1 != 15 || v2 != 22 || v3 != 22) {
    fprintf(stderr, "actor results wrong: %lld %lld %lld\n",
            (long long)v1, (long long)v2, (long long)v3);
    return 1;
  }
  printf("ACTOR add=15,22 total=22\n");
  if (!c.KillActor(aid, true)) return 1;
  // Calls after kill fail cleanly on the client plane.
  if (!c.CallActor(aid, "total", {}).empty()) return 1;
  printf("ACTOR killed\n");

  // Placement group from C++ (no Python on this side): reserve a CPU
  // bundle, place an actor inside the reservation, then tear it down.
  bool pg_ready = false;
  std::string pgid = c.CreatePlacementGroup(
      {{{"CPU", 1.0}}}, "PACK", "cpp-pg", 30.0, &pg_ready);
  if (pgid.empty() || !pg_ready) {
    fprintf(stderr, "create_pg: %s\n", c.error().c_str());
    return 1;
  }
  std::string paid = c.CreateActor("tests.xlang_helpers.CppCounter",
                                   {raytpu_client::Client::I64(1)}, 1.0,
                                   "", pgid, 0);
  if (paid.empty()) {
    fprintf(stderr, "pg actor: %s\n", c.error().c_str());
    return 1;
  }
  std::string pr = c.CallActor(paid, "add",
                               {raytpu_client::Client::I64(2)});
  v = c.Get(pr, 60, &found);
  int64_t pv = 0;
  if (!found || v.format() != "i64") return 1;
  memcpy(&pv, v.data().data(), 8);
  if (pv != 3) {
    fprintf(stderr, "pg actor result wrong: %lld\n", (long long)pv);
    return 1;
  }
  printf("PG actor=3\n");
  c.KillActor(paid, true);
  if (!c.RemovePlacementGroup(pgid)) return 1;
  if (c.RemovePlacementGroup(pgid)) return 1;  // idempotence: gone now
  printf("PG removed\n");
  printf("ALL OK\n");
  return 0;
}
