// Shared machinery of the native scheduling cores (the raylet-split's
// C++ halves): cpp/agent_core.cc owns the AGENT's select round,
// cpp/head_core.cc owns the HEAD's — both are built from the pieces
// here so the wire contract lives in exactly one place:
//
//   * the FRAME PUMP (`FramePump`) — epoll readiness, MSG_DONTWAIT
//     reads into per-connection buffers, outer-frame splitting (the
//     <Q len><I nbufs>[<Q blen>...] framing of core/transport.py,
//     proto-flag frames included), accept-socket readiness surfacing,
//     and the pickle-prefix op sniffer;
//   * the RESTRICTED UNPICKLER (`PickleWalk`) — walks the C-pickler
//     output of the few hot frame shapes and BAILS on any opcode
//     outside its contract, so an unexpected payload is a slow frame,
//     never a wrong one;
//   * the NATIVE PICKLE WRITERS — hand-rolled protocol-5 builds of the
//     fixed hot-frame shapes (exec_raw / reg_fn / node_done_raw /
//     node_exec_raw) into complete outer frames.
//
// Wire-contract note (tools/staticcheck wire-drift): the AgentFrame
// oneof tags used by the proto sniffer (kAgentFrameTags) are pinned
// BOTH WAYS against ray_tpu/protocol/raytpu.proto — a renumber or
// rename on either side is a tier-1 failure, not a silent misroute.
//
// Everything is `static`/header-local: each core compiles into its own
// .so through the content-hash g++ cache (ray_tpu/_native/build.py
// hashes this header alongside the .cc, so edits here rebuild both).

#ifndef RAY_TPU_FRAME_CORE_H_
#define RAY_TPU_FRAME_CORE_H_

#include <errno.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <deque>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

namespace framecore {

// ---- outer framing (must match core/transport.py) ----
static const uint32_t PROTO_FLAG = 0x80000000u;

// AgentFrame oneof field tags (ray_tpu/protocol/raytpu.proto). The pump
// labels proto-framed control messages by their outermost tag so Python
// can route without a trial decode; staticcheck pins these both ways
// against the .proto. Wire type is always 2 (length-delimited
// submessage).
struct AgentFrameTag { int field; const char* name; };
static const AgentFrameTag kAgentFrameTags[] = {
    {1, "register_node"}, {2, "heartbeat"}, {3, "node_ack"},
    {4, "worker_death"}, {5, "spawn_worker"}, {6, "kill_worker"},
    {7, "fetch"}, {8, "fetched"}, {9, "free_object"}, {10, "seq_skip"},
    {11, "cluster_view"}, {12, "lease_spilled"}, {13, "task_events"},
    {14, "metrics_update"},
};

static inline int agent_frame_tag_count() {
  return (int)(sizeof(kAgentFrameTags) / sizeof(kAgentFrameTags[0]));
}

static inline int agent_frame_tag_entry(int i, int* field,
                                        const char** name) {
  if (i < 0 || i >= agent_frame_tag_count()) return -1;
  *field = kAgentFrameTags[i].field;
  *name = kAgentFrameTags[i].name;
  return 0;
}

// ---- pickle opcodes (protocol 5, CPython C pickler output) ----
enum : uint8_t {
  OP_PROTO = 0x80, OP_FRAME = 0x95, OP_STOP = '.',
  OP_NONE = 'N', OP_NEWTRUE = 0x88, OP_NEWFALSE = 0x89,
  OP_BININT = 'J', OP_BININT1 = 'K', OP_BININT2 = 'M', OP_LONG1 = 0x8a,
  OP_BINFLOAT = 'G',
  OP_SHORT_BINBYTES = 'C', OP_BINBYTES = 'B', OP_BINBYTES8 = 0x8e,
  OP_SHORT_BINUNICODE = 0x8c, OP_BINUNICODE = 'X', OP_BINUNICODE8 = 0x8d,
  OP_EMPTY_LIST = ']', OP_EMPTY_TUPLE = ')', OP_MARK = '(',
  OP_TUPLE1 = 0x85, OP_TUPLE2 = 0x86, OP_TUPLE3 = 0x87, OP_TUPLE = 't',
  OP_APPEND = 'a', OP_APPENDS = 'e',
  OP_MEMOIZE = 0x94, OP_BINGET = 'h', OP_LONG_BINGET = 'j',
  OP_NEXT_BUFFER = 0x97, OP_READONLY_BUFFER = 0x98,
};

struct PVal {
  enum Kind { NONE, BOOL, INT, FLOAT, BYTES, STR, LIST, TUPLE,
              OPAQUE } kind;
  int64_t i = 0;
  double f = 0.0;              // FLOAT (BINFLOAT payloads)
  const uint8_t* p = nullptr;  // BYTES/STR view into the frame buffer
  uint64_t len = 0;
  std::vector<int> items;      // LIST/TUPLE arena ids
};

// Restricted pickle walker: builds an arena of PVals (stack holds arena
// ids so memo aliasing — a BINGET of a list later APPENDS-mutated —
// stays correct). Returns the arena id of the root value, or -1 to bail.
struct PickleWalk {
  std::deque<PVal> arena;
  std::vector<int> stack;
  std::vector<int> marks;
  std::vector<int> memo;

  int push(PVal&& v) {
    arena.emplace_back(std::move(v));
    stack.push_back((int)arena.size() - 1);
    return stack.back();
  }

  int parse(const uint8_t* d, uint64_t n) {
    uint64_t i = 0;
    while (i < n) {
      uint8_t op = d[i++];
      switch (op) {
        case OP_PROTO: if (i + 1 > n) return -1; i += 1; break;
        case OP_FRAME: if (i + 8 > n) return -1; i += 8; break;
        case OP_NONE: push({PVal::NONE}); break;
        case OP_NEWTRUE: { PVal v{PVal::BOOL}; v.i = 1; push(std::move(v)); break; }
        case OP_NEWFALSE: { PVal v{PVal::BOOL}; v.i = 0; push(std::move(v)); break; }
        case OP_BININT: {
          if (i + 4 > n) return -1;
          int32_t x; memcpy(&x, d + i, 4); i += 4;
          PVal v{PVal::INT}; v.i = x; push(std::move(v)); break;
        }
        case OP_BININT1: {
          if (i + 1 > n) return -1;
          PVal v{PVal::INT}; v.i = d[i]; i += 1; push(std::move(v)); break;
        }
        case OP_BININT2: {
          if (i + 2 > n) return -1;
          uint16_t x; memcpy(&x, d + i, 2); i += 2;
          PVal v{PVal::INT}; v.i = x; push(std::move(v)); break;
        }
        case OP_LONG1: {
          if (i + 1 > n) return -1;
          uint8_t k = d[i]; i += 1;
          if (i + k > n || k > 8) return -1;
          int64_t x = 0;
          for (int b = 0; b < k; b++) x |= (int64_t)d[i + b] << (8 * b);
          if (k && (d[i + k - 1] & 0x80))  // sign-extend
            for (int b = k; b < 8; b++) x |= (int64_t)0xff << (8 * b);
          i += k;
          PVal v{PVal::INT}; v.i = x; push(std::move(v)); break;
        }
        case OP_BINFLOAT: {
          if (i + 8 > n) return -1;
          // big-endian IEEE double (pickle spec)
          uint64_t u = 0;
          for (int b = 0; b < 8; b++) u = (u << 8) | d[i + b];
          i += 8;
          PVal v{PVal::FLOAT};
          memcpy(&v.f, &u, 8);
          push(std::move(v)); break;
        }
        case OP_SHORT_BINBYTES: case OP_SHORT_BINUNICODE: {
          if (i + 1 > n) return -1;
          uint64_t k = d[i]; i += 1;
          if (i + k > n) return -1;
          PVal v{op == OP_SHORT_BINBYTES ? PVal::BYTES : PVal::STR};
          v.p = d + i; v.len = k; i += k; push(std::move(v)); break;
        }
        case OP_BINBYTES: case OP_BINUNICODE: {
          if (i + 4 > n) return -1;
          uint32_t k; memcpy(&k, d + i, 4); i += 4;
          if (i + k > n) return -1;
          PVal v{op == OP_BINBYTES ? PVal::BYTES : PVal::STR};
          v.p = d + i; v.len = k; i += k; push(std::move(v)); break;
        }
        case OP_BINBYTES8: case OP_BINUNICODE8: {
          if (i + 8 > n) return -1;
          uint64_t k; memcpy(&k, d + i, 8); i += 8;
          if (k > n || i + k > n) return -1;
          PVal v{op == OP_BINBYTES8 ? PVal::BYTES : PVal::STR};
          v.p = d + i; v.len = k; i += k; push(std::move(v)); break;
        }
        case OP_EMPTY_LIST: push({PVal::LIST}); break;
        case OP_EMPTY_TUPLE: push({PVal::TUPLE}); break;
        case OP_MARK: marks.push_back((int)stack.size()); break;
        case OP_APPEND: {
          if (stack.size() < 2) return -1;
          int it = stack.back(); stack.pop_back();
          PVal& l = arena[stack.back()];
          if (l.kind != PVal::LIST) return -1;
          l.items.push_back(it); break;
        }
        case OP_APPENDS: {
          if (marks.empty()) return -1;
          int m = marks.back(); marks.pop_back();
          if ((int)stack.size() < m || m < 1) return -1;
          PVal& l = arena[stack[m - 1]];
          if (l.kind != PVal::LIST) return -1;
          for (int j = m; j < (int)stack.size(); j++) l.items.push_back(stack[j]);
          stack.resize(m); break;
        }
        case OP_TUPLE1: case OP_TUPLE2: case OP_TUPLE3: {
          int k = op - OP_TUPLE1 + 1;
          if ((int)stack.size() < k) return -1;
          PVal v{PVal::TUPLE};
          v.items.assign(stack.end() - k, stack.end());
          stack.resize(stack.size() - k);
          push(std::move(v)); break;
        }
        case OP_TUPLE: {
          if (marks.empty()) return -1;
          int m = marks.back(); marks.pop_back();
          if ((int)stack.size() < m) return -1;
          PVal v{PVal::TUPLE};
          v.items.assign(stack.begin() + m, stack.end());
          stack.resize(m);
          push(std::move(v)); break;
        }
        case OP_MEMOIZE:
          if (stack.empty()) return -1;
          memo.push_back(stack.back()); break;
        case OP_BINGET: {
          if (i + 1 > n) return -1;
          uint8_t k = d[i]; i += 1;
          if (k >= memo.size()) return -1;
          stack.push_back(memo[k]); break;
        }
        case OP_LONG_BINGET: {
          if (i + 4 > n) return -1;
          uint32_t k; memcpy(&k, d + i, 4); i += 4;
          if (k >= memo.size()) return -1;
          stack.push_back(memo[k]); break;
        }
        case OP_NEXT_BUFFER: push({PVal::OPAQUE}); break;
        case OP_READONLY_BUFFER: break;  // wraps top in place
        case OP_STOP:
          if (stack.size() != 1) return -1;
          return stack.back();
        default:
          return -1;  // outside the contract: Python owns this frame
      }
    }
    return -1;
  }
};

// Cheap op sniff: the first string literal pushed in a C-pickled tuple
// ("op", ...) is the op. Returns length of op copied into out (0 = unknown).
static int sniff_op(const uint8_t* d, uint64_t n, char* out, int cap) {
  uint64_t i = 0;
  if (i + 2 <= n && d[i] == OP_PROTO) i += 2;
  if (i + 9 <= n && d[i] == OP_FRAME) i += 9;
  while (i < n && d[i] == OP_MARK) i += 1;  // 4+-tuples open with MARK
  if (i >= n) return 0;
  uint64_t k = 0;
  if (d[i] == OP_SHORT_BINUNICODE) {
    if (i + 2 > n) return 0;
    k = d[i + 1]; i += 2;
  } else if (d[i] == OP_BINUNICODE) {
    if (i + 5 > n) return 0;
    uint32_t kk; memcpy(&kk, d + i + 1, 4); k = kk; i += 5;
  } else {
    return 0;
  }
  if (k == 0 || k >= (uint64_t)cap || i + k > n) return 0;
  memcpy(out, d + i, k);
  out[k] = 0;
  return (int)k;
}

// ---- native pickle writers for the fixed hot-frame shapes ----

static void put_u64(std::string& o, uint64_t v) { o.append((const char*)&v, 8); }
static void put_u32(std::string& o, uint32_t v) { o.append((const char*)&v, 4); }

static void pk_bytes(std::string& o, const uint8_t* p, uint64_t n) {
  if (n < 256) {
    o.push_back((char)OP_SHORT_BINBYTES);
    o.push_back((char)n);
  } else if (n <= 0xffffffffu) {
    o.push_back((char)OP_BINBYTES);
    put_u32(o, (uint32_t)n);
  } else {
    o.push_back((char)OP_BINBYTES8);
    put_u64(o, n);
  }
  o.append((const char*)p, n);
}

static void pk_str(std::string& o, const char* s) {
  size_t n = strlen(s);
  o.push_back((char)OP_SHORT_BINUNICODE);
  o.push_back((char)n);
  o.append(s, n);
}

static void pk_strn(std::string& o, const uint8_t* p, uint64_t n) {
  if (n < 256) {
    o.push_back((char)OP_SHORT_BINUNICODE);
    o.push_back((char)n);
  } else {
    o.push_back((char)OP_BINUNICODE);
    put_u32(o, (uint32_t)n);
  }
  o.append((const char*)p, n);
}

static void pk_none(std::string& o) { o.push_back((char)OP_NONE); }

static void pk_int(std::string& o, int64_t v) {
  if (v >= 0 && v < 256) {
    o.push_back((char)OP_BININT1);
    o.push_back((char)v);
  } else if (v >= 0 && v < 65536) {
    o.push_back((char)OP_BININT2);
    o.push_back((char)(v & 0xff));
    o.push_back((char)(v >> 8));
  } else if (v >= INT32_MIN && v <= INT32_MAX) {
    o.push_back((char)OP_BININT);
    int32_t x = (int32_t)v;
    o.append((const char*)&x, 4);
  } else {
    o.push_back((char)OP_LONG1);
    o.push_back((char)8);
    o.append((const char*)&v, 8);
  }
}

static void pk_proto(std::string& o) {
  o.push_back((char)OP_PROTO);
  o.push_back((char)5);
}

// One complete outer frame carrying pickled `payload` (no oob buffers).
static void frame_wrap(std::string& out, const std::string& payload) {
  put_u64(out, payload.size());
  put_u32(out, 0);
  out += payload;
}

// ("exec_raw", <spec bytes>) as a complete outer frame.
static void build_exec_raw(std::string& out, const std::string& spec) {
  std::string p;
  pk_proto(p);
  pk_str(p, "exec_raw");
  pk_bytes(p, (const uint8_t*)spec.data(), spec.size());
  p.push_back((char)OP_TUPLE2);
  p.push_back((char)OP_STOP);
  frame_wrap(out, p);
}

// ("reg_fn", <fn bytes>, <blob bytes>) as a complete outer frame.
static void build_reg_fn(std::string& out, const std::string& fn,
                         const std::string& blob) {
  std::string p;
  pk_proto(p);
  pk_str(p, "reg_fn");
  pk_bytes(p, (const uint8_t*)fn.data(), fn.size());
  pk_bytes(p, (const uint8_t*)blob.data(), blob.size());
  p.push_back((char)OP_TUPLE3);
  p.push_back((char)OP_STOP);
  frame_wrap(out, p);
}

// ("node_done_raw", <worker hex str>, [<raw frame bytes>, ...]).
static void build_node_done_raw(std::string& out, const std::string& whex,
                                const std::vector<std::string>& raws) {
  std::string p;
  pk_proto(p);
  pk_str(p, "node_done_raw");
  pk_str(p, whex.c_str());
  p.push_back((char)OP_EMPTY_LIST);
  p.push_back((char)OP_MARK);
  for (const auto& r : raws)
    pk_bytes(p, (const uint8_t*)r.data(), r.size());
  p.push_back((char)OP_APPENDS);
  p.push_back((char)OP_TUPLE3);
  p.push_back((char)OP_STOP);
  frame_wrap(out, p);
}

// ---- the frame pump ----

// Connection modes: PICKLE conns are outer-frame split, RAW conns hand
// their chunks to Python unsplit (the cpp-worker protobuf plane), ACCEPT
// conns are listening sockets — readiness surfaces as a KIND_ACCEPT
// record and Python runs accept() (the fd is never recv()'d here).
enum ConnMode { CONN_PICKLE = 0, CONN_RAW = 1, CONN_ACCEPT = 2 };

struct Conn {
  int fd = -1;
  uint64_t tag = 0;
  int mode = CONN_PICKLE;
  bool eof = false;
  bool accept_ready = false;  // ACCEPT conns: readiness latched this round
  std::string buf;            // unconsumed inbound bytes
  size_t scan = 0;            // split cursor into buf
};

// Frame kinds surfaced to Python (mirrored in the ctypes bindings).
enum FrameKind { KIND_PICKLE = 0, KIND_PROTO = 1, KIND_RAW = 2,
                 KIND_EOF = 3, KIND_ACCEPT = 4 };

struct Frame {
  uint64_t tag;
  int kind;               // FrameKind
  int proto_tag = 0;      // KIND_PROTO: AgentFrame oneof field tag (0 unknown)
  const uint8_t* whole = nullptr;  // full frame incl. outer header
  uint64_t whole_len = 0;
  const uint8_t* payload = nullptr;
  uint64_t payload_len = 0;
  std::vector<std::pair<const uint8_t*, uint64_t>> bufs;
  char op[24] = {0};      // sniffed op ("" = not sniffable)
  bool consumed = false;
};

// The epoll pump + splitter. NOT internally synchronized: the owning
// core's mutex guards every method except poll()'s epoll_wait (which
// runs unlocked on the single pump thread; only the buffer drain takes
// the lock — both cores keep that discipline).
struct FramePump {
  int ep = -1;
  std::unordered_map<int, Conn> conns;          // fd -> conn
  std::vector<epoll_event> events;
  std::vector<Frame> frames;
  // Buffers of conns del_fd'ed mid-round: frame views may still point
  // into them, so ownership parks here until round_end() (a del_fd from
  // a death path running inside the round must never dangle a view).
  std::vector<std::string> dead_bufs;

  void init() { ep = epoll_create1(EPOLL_CLOEXEC); }
  void close_ep() {
    if (ep >= 0) close(ep);
    ep = -1;
  }

  int add_fd(int fd, uint64_t tag, int mode) {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    if (epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ev) != 0) return -1;
    Conn& cn = conns[fd];
    cn.fd = fd;
    cn.tag = tag;
    cn.mode = mode;
    cn.eof = false;
    cn.accept_ready = false;
    cn.buf.clear();
    cn.scan = 0;
    return 0;
  }

  int del_fd(int fd) {
    epoll_ctl(ep, EPOLL_CTL_DEL, fd, nullptr);
    auto it = conns.find(fd);
    if (it != conns.end()) {
      if (!it->second.buf.empty())
        dead_bufs.emplace_back(std::move(it->second.buf));
      conns.erase(it);
    }
    return 0;
  }

  // epoll_wait half of poll(): runs WITHOUT the core lock.
  int wait(int timeout_ms) {
    events.resize(64);
    return epoll_wait(ep, events.data(), (int)events.size(), timeout_ms);
  }

  // Drain half of poll(): caller holds the core lock. Returns the number
  // of conns with new data / EOF / pending accepts.
  int drain(int nev) {
    int active = 0;
    char tmp[1 << 18];
    for (int i = 0; i < nev; i++) {
      int fd = events[i].data.fd;
      auto it = conns.find(fd);
      if (it == conns.end()) continue;
      Conn& cn = it->second;
      if (cn.mode == CONN_ACCEPT) {
        cn.accept_ready = true;
        active++;
        continue;
      }
      bool got = false;
      for (;;) {
        ssize_t r = recv(fd, tmp, sizeof(tmp), MSG_DONTWAIT);
        if (r > 0) {
          cn.buf.append(tmp, (size_t)r);
          got = true;
          if ((size_t)r < sizeof(tmp)) break;
          continue;
        }
        if (r == 0) {
          cn.eof = true;
          got = true;
          break;
        }
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        cn.eof = true;  // hard error: surface as EOF, Python runs death path
        got = true;
        break;
      }
      if (got) active++;
    }
    return active;
  }

  // Split buffered bytes into frames (per conn, in order). Raw-mode conns
  // yield one KIND_RAW chunk per round; accept-ready conns one
  // KIND_ACCEPT record; EOF yields a trailing KIND_EOF record.
  // Frame views stay valid until round_end().
  int split() {
    frames.clear();
    for (auto& kv : conns) {
      Conn& cn = kv.second;
      if (cn.mode == CONN_ACCEPT) {
        if (cn.accept_ready) {
          cn.accept_ready = false;
          Frame f;
          f.tag = cn.tag;
          f.kind = KIND_ACCEPT;
          frames.push_back(std::move(f));
        }
        continue;
      }
      if (cn.mode == CONN_RAW) {
        if (cn.scan < cn.buf.size()) {
          Frame f;
          f.tag = cn.tag;
          f.kind = KIND_RAW;
          f.payload = (const uint8_t*)cn.buf.data() + cn.scan;
          f.payload_len = cn.buf.size() - cn.scan;
          cn.scan = cn.buf.size();
          frames.push_back(std::move(f));
        }
      } else {
        const uint8_t* d = (const uint8_t*)cn.buf.data();
        size_t n = cn.buf.size();
        while (cn.scan + 12 <= n) {
          uint64_t plen;
          uint32_t nbufs;
          memcpy(&plen, d + cn.scan, 8);
          memcpy(&nbufs, d + cn.scan + 8, 4);
          Frame f;
          f.tag = cn.tag;
          if (nbufs & PROTO_FLAG) {
            uint64_t total = 12 + plen;
            if (cn.scan + total > n) break;
            f.kind = KIND_PROTO;
            f.whole = d + cn.scan;
            f.whole_len = total;
            f.payload = d + cn.scan + 12;
            f.payload_len = plen;
            // outermost submessage tag of the AgentFrame (varint key)
            if (plen >= 1) {
              uint8_t key = f.payload[0];
              if ((key & 7) == 2) f.proto_tag = key >> 3;
            }
            cn.scan += total;
          } else {
            if (nbufs > 4096) { cn.eof = true; break; }  // corrupt header
            uint64_t lens_end = 12 + 8ull * nbufs;
            if (cn.scan + lens_end > n) break;
            uint64_t total = lens_end + plen;
            std::vector<uint64_t> blens(nbufs);
            for (uint32_t b = 0; b < nbufs; b++) {
              memcpy(&blens[b], d + cn.scan + 12 + 8ull * b, 8);
              total += blens[b];
            }
            if (cn.scan + total > n) break;
            f.kind = KIND_PICKLE;
            f.whole = d + cn.scan;
            f.whole_len = total;
            f.payload = d + cn.scan + lens_end;
            f.payload_len = plen;
            uint64_t off = cn.scan + lens_end + plen;
            for (uint32_t b = 0; b < nbufs; b++) {
              f.bufs.emplace_back(d + off, blens[b]);
              off += blens[b];
            }
            sniff_op(f.payload, f.payload_len, f.op, sizeof(f.op));
            cn.scan += total;
          }
          frames.push_back(std::move(f));
        }
      }
      if (cn.eof && cn.scan >= cn.buf.size()) {
        Frame f;
        f.tag = cn.tag;
        f.kind = KIND_EOF;
        frames.push_back(std::move(f));
      }
    }
    return (int)frames.size();
  }

  // End of round: drop consumed bytes from conn buffers and clear the
  // frame list (all frame views become invalid).
  void round_end() {
    frames.clear();
    dead_bufs.clear();
    for (auto& kv : conns) {
      Conn& cn = kv.second;
      if (cn.scan > 0) {
        cn.buf.erase(0, cn.scan);
        cn.scan = 0;
      }
    }
  }

  int frame_info(int i, uint64_t* tag, int* kind, int* proto_tag,
                 const uint8_t** payload, uint64_t* plen,
                 const uint8_t** whole, uint64_t* wlen, int* nbufs,
                 int* consumed) {
    if (i < 0 || i >= (int)frames.size()) return -1;
    Frame& f = frames[i];
    *tag = f.tag;
    *kind = f.kind;
    *proto_tag = f.proto_tag;
    *payload = f.payload;
    *plen = f.payload_len;
    *whole = f.whole;
    *wlen = f.whole_len;
    *nbufs = (int)f.bufs.size();
    *consumed = f.consumed ? 1 : 0;
    return 0;
  }

  int frame_buf(int i, int j, const uint8_t** p, uint64_t* n) {
    if (i < 0 || i >= (int)frames.size()) return -1;
    Frame& f = frames[i];
    if (j < 0 || j >= (int)f.bufs.size()) return -1;
    *p = f.bufs[j].first;
    *n = f.bufs[j].second;
    return 0;
  }
};

struct Lock {
  pthread_mutex_t* m;
  explicit Lock(pthread_mutex_t* mm) : m(mm) { pthread_mutex_lock(m); }
  ~Lock() { pthread_mutex_unlock(m); }
};

}  // namespace framecore

#endif  // RAY_TPU_FRAME_CORE_H_
