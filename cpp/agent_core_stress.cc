// TSan run-mode storm over the native select-round core's lease ledger +
// dispatch tables (cpp/agent_core.cc). Contract-correct multi-threaded use:
//
//   * producers push leases (agc_seen dedup + agc_push) the way the head's
//     node_exec_raw ingest and the spill-accept path do;
//   * a dispatcher thread plans (agc_dispatch), drains outboxes
//     (agc_take_outbox) and drecs — the agent main loop's role;
//   * a completer pops inflight entries (agc_inflight_pop) like the done
//     path, racing the dispatcher;
//   * a stealer runs agc_steal_tail / agc_fail_worker — the spill/reclaim
//     and worker-death cold paths;
//   * worker churn adds/removes workers and flips eligibility mid-storm.
//
// Every operation here is legal concurrent API use, so any TSan report is
// an agent_core bug, not a harness artifact. Run with
// TSAN_OPTIONS=halt_on_error=1 (tests/test_sanitizers.py).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* agc_new();
void agc_free(void*);
int agc_worker_add(void*, uint64_t, int, const uint8_t*, int, const char*,
                   int);
void agc_worker_remove(void*, int);
void agc_worker_eligible(void*, int, int);
void agc_load_add(void*, int, int);
int agc_seen(void*, const uint8_t*, int, uint64_t);
int agc_push(void*, const uint8_t*, int, const uint8_t*, int, uint64_t,
             const uint8_t*, uint64_t, int64_t, const uint8_t*, int, int);
void agc_fn_blob(void*, const uint8_t*, int, const uint8_t*, uint64_t);
uint64_t agc_backlog(void*);
uint64_t agc_inflight(void*);
int agc_idle(void*);
int agc_dispatch(void*, int, int);
int agc_outbox_widx(void*, int);
int agc_take_outbox(void*, int, const uint8_t**, uint64_t*);
int agc_drec_count(void*);
int agc_drec(void*, int, const uint8_t**, uint64_t*, int*, int64_t*,
             const uint8_t**, uint64_t*);
int agc_inflight_pop(void*, const uint8_t*, int);
int agc_steal_tail(void*, int);
int agc_fail_worker(void*, int);
int agc_stolen(void*, int, const uint8_t**, uint64_t*, const uint8_t**,
               uint64_t*, uint64_t*, const uint8_t**, uint64_t*);
void agc_stats(void*, uint64_t*, uint64_t*, uint64_t*);
}

namespace {

constexpr int kWorkers = 6;
constexpr int kProducers = 3;
constexpr int kTasksPerProducer = 4000;

std::atomic<bool> g_stop{false};
std::atomic<uint64_t> g_pushed{0}, g_dispatched{0}, g_completed{0},
    g_stolen{0}, g_failed{0};

void make_tid(uint8_t* out, int producer, int i) {
  memset(out, 0, 16);
  out[0] = (uint8_t)producer;
  memcpy(out + 1, &i, sizeof(i));
}

void producer(void* c, int id) {
  uint8_t tid[16], fn[16];
  memset(fn, 0x41 + id, 16);
  uint8_t blob[64];
  memset(blob, 0x55, sizeof(blob));
  agc_fn_blob(c, fn, 16, blob, sizeof(blob));
  std::string spec(180 + id * 7, (char)('a' + id));
  for (int i = 0; i < kTasksPerProducer; i++) {
    make_tid(tid, id, i);
    uint64_t seq = 1 + (i % 3);
    if (agc_seen(c, tid, 16, seq)) continue;
    agc_push(c, tid, 16, fn, 16, seq, (const uint8_t*)spec.data(),
             spec.size(), i % 4, (const uint8_t*)"stress", 6, i % 17 == 0);
    g_pushed.fetch_add(1, std::memory_order_relaxed);
    if (i % 64 == 0) agc_seen(c, tid, 16, seq);  // re-drive dedup path
  }
}

void dispatcher(void* c) {
  const uint8_t* p;
  uint64_t n;
  while (!g_stop.load(std::memory_order_acquire)) {
    int k = agc_dispatch(c, 8, 1);
    for (int i = 0; i < k; i++) {
      int widx = agc_outbox_widx(c, i);
      if (widx >= 0 && agc_take_outbox(c, widx, &p, &n) == 0 && n > 0)
        g_dispatched.fetch_add(1, std::memory_order_relaxed);
    }
    const uint8_t *tp, *np;
    uint64_t tl, nl;
    int widx;
    int64_t att;
    int dr = agc_drec_count(c);
    for (int i = 0; i < dr; i++)
      agc_drec(c, i, &tp, &tl, &widx, &att, &np, &nl);
    agc_backlog(c);
    agc_idle(c);
  }
}

// Completions: replay every possible tid through inflight_pop, racing the
// dispatcher that inserts them.
void completer(void* c) {
  uint8_t tid[16];
  while (!g_stop.load(std::memory_order_acquire)) {
    for (int pr = 0; pr < kProducers; pr++) {
      for (int i = 0; i < kTasksPerProducer; i += 7) {
        make_tid(tid, pr, i);
        if (agc_inflight_pop(c, tid, 16) >= 0)
          g_completed.fetch_add(1, std::memory_order_relaxed);
      }
    }
  }
}

void stealer(void* c) {
  while (!g_stop.load(std::memory_order_acquire)) {
    int n = agc_steal_tail(c, 16);
    const uint8_t *tp, *fp, *sp;
    uint64_t tl, fl, sl, seq;
    for (int i = 0; i < n; i++) {
      if (agc_stolen(c, i, &tp, &tl, &fp, &fl, &seq, &sp, &sl) == 0) {
        // push the stolen lease back (the hop-capped / reclaim path)
        agc_push(c, tp, (int)tl, fp, (int)fl, seq, sp, sl, 0, nullptr, 0,
                 0);
        g_stolen.fetch_add(1, std::memory_order_relaxed);
      }
    }
    std::this_thread::yield();
  }
}

void churner(void* c, int base_widx) {
  uint8_t wid[8];
  int flip = 0;
  while (!g_stop.load(std::memory_order_acquire)) {
    memset(wid, 0x77, 8);
    int w = agc_worker_add(c, 1000 + flip, -1, wid, 8, "deadbeefdead", 1);
    agc_load_add(c, w, 1);
    agc_load_add(c, w, -1);
    int n = agc_fail_worker(c, w);
    if (n) g_failed.fetch_add(n, std::memory_order_relaxed);
    agc_worker_remove(c, w);
    agc_worker_eligible(c, base_widx + (flip % kWorkers), flip & 1);
    agc_worker_eligible(c, base_widx + (flip % kWorkers), 1);
    flip++;
    std::this_thread::yield();
  }
}

}  // namespace

int main() {
  void* c = agc_new();
  uint8_t wid[8];
  for (int i = 0; i < kWorkers; i++) {
    memset(wid, i, 8);
    agc_worker_add(c, 100 + i, -1, wid, 8, "aabbccddeeff0011", 1);
  }
  std::vector<std::thread> ts;
  ts.emplace_back(dispatcher, c);
  ts.emplace_back(completer, c);
  ts.emplace_back(stealer, c);
  ts.emplace_back(churner, c, 0);
  for (int i = 0; i < kProducers; i++) ts.emplace_back(producer, c, i);
  for (size_t i = ts.size() - kProducers; i < ts.size(); i++) ts[i].join();
  ts.resize(ts.size() - kProducers);
  // drain: let the dispatcher/completer race over the tail for a moment
  for (int spin = 0; spin < 200 && agc_backlog(c) > 0; spin++)
    agc_dispatch(c, 8, 0);
  g_stop.store(true, std::memory_order_release);
  for (auto& t : ts) t.join();
  uint64_t grants, dones, dispatched;
  agc_stats(c, &grants, &dones, &dispatched);
  printf("pushed=%llu planner_dispatched=%llu completed=%llu stolen=%llu "
         "failed=%llu backlog=%llu inflight=%llu\n",
         (unsigned long long)g_pushed.load(),
         (unsigned long long)dispatched,
         (unsigned long long)g_completed.load(),
         (unsigned long long)g_stolen.load(),
         (unsigned long long)g_failed.load(),
         (unsigned long long)agc_backlog(c),
         (unsigned long long)agc_inflight(c));
  bool ok = g_pushed.load() > 0 && dispatched > 0 && g_completed.load() > 0;
  agc_free(c);
  if (!ok) {
    fprintf(stderr, "stress exercised too little of the ledger\n");
    return 2;
  }
  printf("AGENT_CORE_STRESS_OK\n");
  return 0;
}
