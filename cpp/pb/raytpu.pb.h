// Hand-rolled protobuf bindings for ray_tpu/protocol/raytpu.proto.
//
// This build environment ships no protoc and no libprotobuf, so the C++
// frontend (raytpu_client.cc) and the C++ worker runtime
// (raytpu_worker.cc) encode/decode the schema with a small varint codec
// implemented here — byte-compatible with the protobuf wire format the
// Python side speaks through google.protobuf (the relationship mirrors
// core/proto_wire.py: the .proto file is the contract, the codec is
// hand-maintained). Only the fields the C++ sources use are materialized;
// unknown fields are skipped on parse, so the header stays forward
// compatible with schema growth. When a real protoc is available the
// generated raytpu.pb.h is a drop-in replacement (the API below matches
// the generated accessors the client code was written against).
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace pbwire {

// ---- wire primitives (proto wire types 0=varint, 1=fixed64, 2=len) ----

inline void PutVarint(std::string* out, uint64_t v) {
  while (v >= 0x80) {
    out->push_back(static_cast<char>((v & 0x7f) | 0x80));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}

inline void PutTag(std::string* out, int field, int wt) {
  PutVarint(out, (static_cast<uint64_t>(field) << 3) | wt);
}

inline void PutLenField(std::string* out, int field, const std::string& s) {
  if (s.empty()) return;
  PutTag(out, field, 2);
  PutVarint(out, s.size());
  out->append(s);
}

// Length-delimited field emitted even when empty (oneof members and
// required-presence submessages must hit the wire to select the arm).
inline void PutLenAlways(std::string* out, int field, const std::string& s) {
  PutTag(out, field, 2);
  PutVarint(out, s.size());
  out->append(s);
}

inline void PutInt(std::string* out, int field, int64_t v) {
  if (v == 0) return;
  PutTag(out, field, 0);
  PutVarint(out, static_cast<uint64_t>(v));
}

inline void PutBool(std::string* out, int field, bool v) {
  if (!v) return;
  PutTag(out, field, 0);
  PutVarint(out, 1);
}

inline void PutDouble(std::string* out, int field, double v) {
  if (v == 0.0) return;
  PutTag(out, field, 1);
  char buf[8];
  memcpy(buf, &v, 8);
  out->append(buf, 8);
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  Reader(const void* data, size_t n)
      : p(static_cast<const uint8_t*>(data)),
        end(static_cast<const uint8_t*>(data) + n) {}

  uint64_t Varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  bool Tag(int* field, int* wt) {
    if (p >= end || !ok) return false;
    uint64_t t = Varint();
    if (!ok) return false;
    *field = static_cast<int>(t >> 3);
    *wt = static_cast<int>(t & 7);
    return true;
  }

  std::string Bytes() {
    uint64_t n = Varint();
    if (!ok || p + n > end) {
      ok = false;
      return "";
    }
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }

  // Zero-copy view of a length-delimited field (valid while the parse
  // buffer lives) — used for nested-message parses.
  bool View(const uint8_t** data, size_t* n) {
    uint64_t len = Varint();
    if (!ok || p + len > end) {
      ok = false;
      return false;
    }
    *data = p;
    *n = len;
    p += len;
    return true;
  }

  double Double() {
    if (p + 8 > end) {
      ok = false;
      return 0.0;
    }
    double v;
    memcpy(&v, p, 8);
    p += 8;
    return v;
  }

  void Skip(int wt) {
    switch (wt) {
      case 0:
        Varint();
        break;
      case 1:
        p += 8;
        break;
      case 2: {
        uint64_t n = Varint();
        if (p + n > end) { ok = false; return; }
        p += n;
        break;
      }
      case 5:
        p += 4;
        break;
      default:
        ok = false;
    }
    if (p > end) ok = false;
  }
};

// map<string, double> encodes as repeated { 1: key, 2: value }.
inline void PutMapSD(std::string* out, int field,
                     const std::map<std::string, double>& m) {
  for (const auto& kv : m) {
    std::string entry;
    PutLenField(&entry, 1, kv.first);
    PutDouble(&entry, 2, kv.second);
    PutLenAlways(out, field, entry);
  }
}

inline void ParseMapSDEntry(const uint8_t* data, size_t n,
                            std::map<std::string, double>* m) {
  Reader r(data, n);
  std::string key;
  double val = 0.0;
  int f, wt;
  while (r.Tag(&f, &wt)) {
    if (f == 1 && wt == 2) key = r.Bytes();
    else if (f == 2 && wt == 1) val = r.Double();
    else r.Skip(wt);
  }
  (*m)[key] = val;
}

}  // namespace pbwire

namespace raytpu {

// ---------- common ----------

class Value {
 public:
  const std::string& data() const { return data_; }
  const std::string& format() const { return format_; }
  void set_data(const std::string& d) { data_ = d; }
  void set_data(const void* d, size_t n) {
    data_.assign(static_cast<const char*>(d), n);
  }
  void set_format(const std::string& f) { format_ = f; }
  void CopyFrom(const Value& o) { *this = o; }

  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, data_);
    pbwire::PutLenField(out, 2, format_);
  }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2) data_ = r.Bytes();
      else if (f == 2 && wt == 2) format_ = r.Bytes();
      else r.Skip(wt);
    }
  }

 private:
  std::string data_;
  std::string format_;
};

class Arg {
 public:
  Value* mutable_value() { has_value_ = true; return &value_; }
  const Value& value() const { return value_; }
  bool has_value() const { return has_value_; }
  void set_object_id(const std::string& oid) { object_id_ = oid; }
  const std::string& object_id() const { return object_id_; }
  bool has_object_id() const { return !object_id_.empty(); }

  void AppendTo(std::string* out) const {
    if (has_value_) {
      std::string v;
      value_.AppendTo(&v);
      pbwire::PutLenAlways(out, 1, v);
    } else if (!object_id_.empty()) {
      pbwire::PutLenField(out, 2, object_id_);
    }
  }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    const uint8_t* d;
    size_t len;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2 && r.View(&d, &len)) {
        has_value_ = true;
        value_.Parse(d, len);
      } else if (f == 2 && wt == 2) {
        object_id_ = r.Bytes();
      } else {
        r.Skip(wt);
      }
    }
  }

 private:
  Value value_;
  bool has_value_ = false;
  std::string object_id_;
};

class TaskArgs {
 public:
  std::vector<Arg> args;  // kwargs are a Python-side concept; skipped

  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    const uint8_t* d;
    size_t len;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2 && r.View(&d, &len)) {
        args.emplace_back();
        args.back().Parse(d, len);
      } else {
        r.Skip(wt);
      }
    }
  }
  void AppendTo(std::string* out) const {
    for (const auto& a : args) {
      std::string buf;
      a.AppendTo(&buf);
      pbwire::PutLenAlways(out, 1, buf);
    }
  }
};

// The dispatch-relevant subset of raytpu.TaskSpec (unknown fields skip).
class TaskSpec {
 public:
  std::string task_id;         // 1
  std::string name;            // 3 — native symbol for cpp tasks
  Value payload;               // 4 — format="task_args"
  std::vector<std::string> return_ids;  // 5
  int32_t max_retries = 0;     // 9
  int32_t retries_left = 0;    // 10

  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    const uint8_t* d;
    size_t len;
    while (r.Tag(&f, &wt)) {
      switch (f) {
        case 1: task_id = r.Bytes(); break;
        case 3: name = r.Bytes(); break;
        case 4:
          if (wt == 2 && r.View(&d, &len)) payload.Parse(d, len);
          break;
        case 5: return_ids.push_back(r.Bytes()); break;
        case 9: max_retries = static_cast<int32_t>(r.Varint()); break;
        case 10: retries_left = static_cast<int32_t>(r.Varint()); break;
        default: r.Skip(wt);
      }
    }
  }
};

// ---------- worker plane (agent <-> non-Python worker) ----------

class WorkerHello {
 public:
  std::string worker_id;             // 1
  int64_t pid = 0;                   // 2
  std::string language;              // 3
  std::vector<std::string> symbols;  // 4

  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, worker_id);
    pbwire::PutInt(out, 2, pid);
    pbwire::PutLenField(out, 3, language);
    for (const auto& s : symbols) pbwire::PutLenField(out, 4, s);
  }
};

class WorkerOut {
 public:
  std::string object_id;  // 1
  std::string status;     // 2 — "shm" | "err"
  Value error;            // 3
  bool has_error = false;

  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, object_id);
    pbwire::PutLenField(out, 2, status);
    if (has_error) {
      std::string e;
      error.AppendTo(&e);
      pbwire::PutLenAlways(out, 3, e);
    }
  }
};

class WorkerDone {
 public:
  std::string task_id;         // 1
  std::vector<WorkerOut> outs; // 2
  int64_t attempt = 0;         // 3
  double exec_start = 0;       // 4
  double args_ready = 0;       // 5
  double exec_done = 0;        // 6
  double seal = 0;             // 7

  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, task_id);
    for (const auto& o : outs) {
      std::string buf;
      o.AppendTo(&buf);
      pbwire::PutLenAlways(out, 2, buf);
    }
    pbwire::PutInt(out, 3, attempt);
    pbwire::PutDouble(out, 4, exec_start);
    pbwire::PutDouble(out, 5, args_ready);
    pbwire::PutDouble(out, 6, exec_done);
    pbwire::PutDouble(out, 7, seal);
  }
};

class WorkerFrame {
 public:
  enum Which { kNone, kHello, kExec, kDone, kShutdown };
  Which which = kNone;
  WorkerHello hello;
  TaskSpec exec_spec;  // WorkerExec{ spec = 1 }

  std::string SerializeHello() const {
    std::string inner;
    hello.AppendTo(&inner);
    std::string out;
    pbwire::PutLenAlways(&out, 1, inner);
    return out;
  }
  static std::string SerializeDone(const WorkerDone& d) {
    std::string inner;
    d.AppendTo(&inner);
    std::string out;
    pbwire::PutLenAlways(&out, 3, inner);
    return out;
  }

  bool Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    const uint8_t* d;
    size_t len;
    while (r.Tag(&f, &wt)) {
      if (f == 2 && wt == 2 && r.View(&d, &len)) {
        which = kExec;
        pbwire::Reader er(d, len);
        int ef, ewt;
        const uint8_t* sd;
        size_t sn;
        while (er.Tag(&ef, &ewt)) {
          if (ef == 1 && ewt == 2 && er.View(&sd, &sn)) exec_spec.Parse(sd, sn);
          else er.Skip(ewt);
        }
      } else if (f == 4 && wt == 2) {
        which = kShutdown;
        r.Skip(wt);
      } else {
        r.Skip(wt);
      }
    }
    return r.ok;
  }
};

// ---------- client plane ----------

class InitRequest {
 public:
  void set_client_name(const std::string& v) { client_name_ = v; }
  void set_client_language(const std::string& v) { client_language_ = v; }
  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, client_name_);
    pbwire::PutLenField(out, 2, client_language_);
  }

 private:
  std::string client_name_, client_language_;
};

class InitReply {
 public:
  const std::map<std::string, double>& cluster_resources() const {
    return resources_;
  }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    const uint8_t* d;
    size_t len;
    while (r.Tag(&f, &wt)) {
      if (f == 3 && wt == 2 && r.View(&d, &len))
        pbwire::ParseMapSDEntry(d, len, &resources_);
      else r.Skip(wt);
    }
  }

 private:
  std::map<std::string, double> resources_;
};

class PutRequest {
 public:
  Value* mutable_value() { return &value_; }
  void AppendTo(std::string* out) const {
    std::string v;
    value_.AppendTo(&v);
    pbwire::PutLenAlways(out, 1, v);
  }

 private:
  Value value_;
};

class PutReply {
 public:
  const std::string& object_id() const { return object_id_; }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2) object_id_ = r.Bytes();
      else r.Skip(wt);
    }
  }

 private:
  std::string object_id_;
};

class GetRequest {
 public:
  void set_object_id(const std::string& v) { object_id_ = v; }
  void set_timeout_s(double v) { timeout_s_ = v; }
  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, object_id_);
    pbwire::PutDouble(out, 2, timeout_s_);
  }

 private:
  std::string object_id_;
  double timeout_s_ = 0;
};

class GetReply {
 public:
  Value value_field;
  bool found_ = false;
  const Value& value() const { return value_field; }
  bool found() const { return found_; }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    const uint8_t* d;
    size_t len;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2 && r.View(&d, &len)) value_field.Parse(d, len);
      else if (f == 2 && wt == 0) found_ = r.Varint() != 0;
      else r.Skip(wt);
    }
  }
};

class SubmitRequest {
 public:
  void set_fn_name(const std::string& v) { fn_name_ = v; }
  void set_num_returns(int v) { num_returns_ = v; }
  Arg* add_args() {
    args_.emplace_back();
    return &args_.back();
  }
  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, fn_name_);
    for (const auto& a : args_) {
      std::string buf;
      a.AppendTo(&buf);
      pbwire::PutLenAlways(out, 2, buf);
    }
    pbwire::PutInt(out, 3, num_returns_);
  }

 private:
  std::string fn_name_;
  std::vector<Arg> args_;
  int num_returns_ = 0;
};

class SubmitReply {
 public:
  const std::vector<std::string>& return_ids() const { return return_ids_; }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2) return_ids_.push_back(r.Bytes());
      else r.Skip(wt);
    }
  }

 private:
  std::vector<std::string> return_ids_;
};

class WaitRequest {
 public:
  void add_object_ids(const std::string& v) { object_ids_.push_back(v); }
  void set_num_returns(int v) { num_returns_ = v; }
  void set_timeout_s(double v) { timeout_s_ = v; }
  void AppendTo(std::string* out) const {
    for (const auto& o : object_ids_) pbwire::PutLenField(out, 1, o);
    pbwire::PutInt(out, 2, num_returns_);
    pbwire::PutDouble(out, 3, timeout_s_);
  }

 private:
  std::vector<std::string> object_ids_;
  int num_returns_ = 0;
  double timeout_s_ = 0;
};

class WaitReply {
 public:
  const std::vector<std::string>& ready() const { return ready_; }
  int ready_size() const { return static_cast<int>(ready_.size()); }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2) ready_.push_back(r.Bytes());
      else r.Skip(wt);
    }
  }

 private:
  std::vector<std::string> ready_;
};

class CreateActorRequest {
 public:
  void set_class_name(const std::string& v) { class_name_ = v; }
  void set_num_cpus(double v) { num_cpus_ = v; }
  void set_name(const std::string& v) { name_ = v; }
  void set_placement_group_id(const std::string& v) { pg_id_ = v; }
  void set_bundle_index(int v) { bundle_index_ = v; }
  Arg* add_args() {
    args_.emplace_back();
    return &args_.back();
  }
  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, class_name_);
    for (const auto& a : args_) {
      std::string buf;
      a.AppendTo(&buf);
      pbwire::PutLenAlways(out, 2, buf);
    }
    pbwire::PutDouble(out, 3, num_cpus_);
    pbwire::PutLenField(out, 6, name_);
    pbwire::PutLenField(out, 7, pg_id_);
    pbwire::PutInt(out, 8, bundle_index_);
  }

 private:
  std::string class_name_, name_, pg_id_;
  std::vector<Arg> args_;
  double num_cpus_ = 0;
  int bundle_index_ = 0;
};

class CreateActorReply {
 public:
  std::string actor_id_;
  const std::string& actor_id() const { return actor_id_; }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2) actor_id_ = r.Bytes();
      else r.Skip(wt);
    }
  }
};

class Bundle {
 public:
  std::map<std::string, double>* mutable_resources() { return &resources_; }
  void AppendTo(std::string* out) const {
    pbwire::PutMapSD(out, 1, resources_);
  }

 private:
  std::map<std::string, double> resources_;
};

class CreatePlacementGroupRequest {
 public:
  Bundle* add_bundles() {
    bundles_.emplace_back();
    return &bundles_.back();
  }
  void set_strategy(const std::string& v) { strategy_ = v; }
  void set_name(const std::string& v) { name_ = v; }
  void set_ready_timeout_s(double v) { ready_timeout_s_ = v; }
  void AppendTo(std::string* out) const {
    for (const auto& b : bundles_) {
      std::string buf;
      b.AppendTo(&buf);
      pbwire::PutLenAlways(out, 1, buf);
    }
    pbwire::PutLenField(out, 2, strategy_);
    pbwire::PutLenField(out, 3, name_);
    pbwire::PutDouble(out, 4, ready_timeout_s_);
  }

 private:
  std::vector<Bundle> bundles_;
  std::string strategy_, name_;
  double ready_timeout_s_ = 0;
};

class CreatePlacementGroupReply {
 public:
  std::string pg_id_;
  bool ready_ = false;
  const std::string& placement_group_id() const { return pg_id_; }
  bool ready() const { return ready_; }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2) pg_id_ = r.Bytes();
      else if (f == 2 && wt == 0) ready_ = r.Varint() != 0;
      else r.Skip(wt);
    }
  }
};

class RemovePlacementGroupRequest {
 public:
  void set_placement_group_id(const std::string& v) { pg_id_ = v; }
  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, pg_id_);
  }

 private:
  std::string pg_id_;
};

class SimpleOkReply {
 public:
  bool ok_ = false;
  bool ok() const { return ok_; }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 0) ok_ = r.Varint() != 0;
      else r.Skip(wt);
    }
  }
};
using RemovePlacementGroupReply = SimpleOkReply;
using KillActorReply = SimpleOkReply;
using KvPutReply = SimpleOkReply;

class ActorCallRequest {
 public:
  void set_actor_id(const std::string& v) { actor_id_ = v; }
  void set_method(const std::string& v) { method_ = v; }
  Arg* add_args() {
    args_.emplace_back();
    return &args_.back();
  }
  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, actor_id_);
    pbwire::PutLenField(out, 2, method_);
    for (const auto& a : args_) {
      std::string buf;
      a.AppendTo(&buf);
      pbwire::PutLenAlways(out, 3, buf);
    }
  }

 private:
  std::string actor_id_, method_;
  std::vector<Arg> args_;
};

class ActorCallReply {
 public:
  std::string return_id_;
  const std::string& return_id() const { return return_id_; }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2) return_id_ = r.Bytes();
      else r.Skip(wt);
    }
  }
};

class KillActorRequest {
 public:
  void set_actor_id(const std::string& v) { actor_id_ = v; }
  void set_no_restart(bool v) { no_restart_ = v; }
  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, actor_id_);
    pbwire::PutBool(out, 2, no_restart_);
  }

 private:
  std::string actor_id_;
  bool no_restart_ = false;
};

class KvPutRequest {
 public:
  void set_key(const std::string& v) { key_ = v; }
  void set_value(const std::string& v) { value_ = v; }
  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, key_);
    pbwire::PutLenField(out, 2, value_);
  }

 private:
  std::string key_, value_;
};

class KvGetRequest {
 public:
  void set_key(const std::string& v) { key_ = v; }
  void AppendTo(std::string* out) const {
    pbwire::PutLenField(out, 1, key_);
  }

 private:
  std::string key_;
};

class KvGetReply {
 public:
  std::string value_;
  bool found_ = false;
  const std::string& value() const { return value_; }
  bool found() const { return found_; }
  void Parse(const uint8_t* data, size_t n) {
    pbwire::Reader r(data, n);
    int f, wt;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 2) value_ = r.Bytes();
      else if (f == 2 && wt == 0) found_ = r.Varint() != 0;
      else r.Skip(wt);
    }
  }
};

// One oneof arm per request type; exactly one is set per RPC.
class ClientRequest {
 public:
  void set_req_id(uint64_t v) { req_id_ = v; }
  InitRequest* mutable_init() { which_ = 2; return &init_; }
  PutRequest* mutable_put() { which_ = 3; return &put_; }
  GetRequest* mutable_get() { which_ = 4; return &get_; }
  SubmitRequest* mutable_submit() { which_ = 5; return &submit_; }
  WaitRequest* mutable_wait() { which_ = 6; return &wait_; }
  KvPutRequest* mutable_kv_put() { which_ = 7; return &kv_put_; }
  KvGetRequest* mutable_kv_get() { which_ = 8; return &kv_get_; }
  CreateActorRequest* mutable_create_actor() {
    which_ = 9;
    return &create_actor_;
  }
  ActorCallRequest* mutable_actor_call() { which_ = 10; return &actor_call_; }
  KillActorRequest* mutable_kill_actor() { which_ = 11; return &kill_actor_; }
  CreatePlacementGroupRequest* mutable_create_placement_group() {
    which_ = 12;
    return &create_pg_;
  }
  RemovePlacementGroupRequest* mutable_remove_placement_group() {
    which_ = 13;
    return &remove_pg_;
  }

  bool SerializeToString(std::string* out) const {
    out->clear();
    pbwire::PutInt(out, 1, static_cast<int64_t>(req_id_));
    std::string body;
    switch (which_) {
      case 2: init_.AppendTo(&body); break;
      case 3: put_.AppendTo(&body); break;
      case 4: get_.AppendTo(&body); break;
      case 5: submit_.AppendTo(&body); break;
      case 6: wait_.AppendTo(&body); break;
      case 7: kv_put_.AppendTo(&body); break;
      case 8: kv_get_.AppendTo(&body); break;
      case 9: create_actor_.AppendTo(&body); break;
      case 10: actor_call_.AppendTo(&body); break;
      case 11: kill_actor_.AppendTo(&body); break;
      case 12: create_pg_.AppendTo(&body); break;
      case 13: remove_pg_.AppendTo(&body); break;
      default: return false;
    }
    pbwire::PutLenAlways(out, which_, body);
    return true;
  }

 private:
  uint64_t req_id_ = 0;
  int which_ = 0;
  InitRequest init_;
  PutRequest put_;
  GetRequest get_;
  SubmitRequest submit_;
  WaitRequest wait_;
  KvPutRequest kv_put_;
  KvGetRequest kv_get_;
  CreateActorRequest create_actor_;
  ActorCallRequest actor_call_;
  KillActorRequest kill_actor_;
  CreatePlacementGroupRequest create_pg_;
  RemovePlacementGroupRequest remove_pg_;
};

class ClientReply {
 public:
  const std::string& error() const { return error_; }
  const InitReply& init() const { return init_; }
  const PutReply& put() const { return put_; }
  const GetReply& get() const { return get_; }
  const SubmitReply& submit() const { return submit_; }
  const WaitReply& wait() const { return wait_; }
  const KvGetReply& kv_get() const { return kv_get_; }
  const KvPutReply& kv_put() const { return kv_put_; }
  const CreateActorReply& create_actor() const { return create_actor_; }
  const ActorCallReply& actor_call() const { return actor_call_; }
  const KillActorReply& kill_actor() const { return kill_actor_; }
  const CreatePlacementGroupReply& create_placement_group() const {
    return create_pg_;
  }
  const RemovePlacementGroupReply& remove_placement_group() const {
    return remove_pg_;
  }

  bool ParseFromString(const std::string& s) {
    pbwire::Reader r(s.data(), s.size());
    int f, wt;
    const uint8_t* d;
    size_t n;
    while (r.Tag(&f, &wt)) {
      if (f == 1 && wt == 0) {
        req_id_ = r.Varint();
      } else if (f == 2 && wt == 2) {
        error_ = r.Bytes();
      } else if (wt == 2 && r.View(&d, &n)) {
        switch (f) {
          case 3: init_.Parse(d, n); break;
          case 4: put_.Parse(d, n); break;
          case 5: get_.Parse(d, n); break;
          case 6: submit_.Parse(d, n); break;
          case 7: wait_.Parse(d, n); break;
          case 8: kv_get_.Parse(d, n); break;
          case 9: kv_put_.Parse(d, n); break;
          case 10: create_actor_.Parse(d, n); break;
          case 11: actor_call_.Parse(d, n); break;
          case 12: kill_actor_.Parse(d, n); break;
          case 13: create_pg_.Parse(d, n); break;
          case 14: remove_pg_.Parse(d, n); break;
          default: break;  // unknown reply arm: ignore
        }
      } else {
        r.Skip(wt);
      }
    }
    return r.ok;
  }

 private:
  uint64_t req_id_ = 0;
  std::string error_;
  InitReply init_;
  PutReply put_;
  GetReply get_;
  SubmitReply submit_;
  WaitReply wait_;
  KvGetReply kv_get_;
  KvPutReply kv_put_;
  CreateActorReply create_actor_;
  ActorCallReply actor_call_;
  KillActorReply kill_actor_;
  CreatePlacementGroupReply create_pg_;
  RemovePlacementGroupReply remove_pg_;
};

}  // namespace raytpu
