#include "raytpu_client.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>

namespace raytpu_client {

namespace {

bool SendAll(int fd, const char* data, size_t n) {
  while (n) {
    ssize_t w = ::send(fd, data, n, 0);
    if (w <= 0) return false;
    data += w;
    n -= w;
  }
  return true;
}

bool RecvAll(int fd, char* data, size_t n) {
  while (n) {
    ssize_t r = ::recv(fd, data, n, 0);
    if (r <= 0) return false;
    data += r;
    n -= r;
  }
  return true;
}

}  // namespace

Client::~Client() {
  if (fd_ >= 0) ::close(fd_);
}

bool Client::Connect(const std::string& host, int port,
                     const std::string& client_name) {
  struct addrinfo hints = {};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                  &res) != 0 || !res) {
    error_ = "resolve failed";
    return false;
  }
  fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  bool ok = fd_ >= 0 && ::connect(fd_, res->ai_addr, res->ai_addrlen) == 0;
  freeaddrinfo(res);
  if (!ok) {
    error_ = "connect failed";
    return false;
  }
  int one = 1;
  setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));

  raytpu::ClientRequest req;
  auto* init = req.mutable_init();
  init->set_client_name(client_name);
  init->set_client_language("cpp");
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply)) return false;
  for (const auto& kv : reply.init().cluster_resources())
    resources_[kv.first] = kv.second;
  return true;
}

bool Client::Rpc(raytpu::ClientRequest* req, raytpu::ClientReply* reply) {
  req->set_req_id(next_req_id_++);
  std::string body;
  if (!req->SerializeToString(&body)) {
    error_ = "serialize failed";
    return false;
  }
  uint32_t len = body.size();
  char hdr[4];
  memcpy(hdr, &len, 4);  // little-endian hosts only (x86/arm)
  if (!SendAll(fd_, hdr, 4) || !SendAll(fd_, body.data(), body.size())) {
    error_ = "send failed";
    return false;
  }
  if (!RecvAll(fd_, hdr, 4)) {
    error_ = "recv failed";
    return false;
  }
  memcpy(&len, hdr, 4);
  std::string rbody(len, '\0');
  if (!RecvAll(fd_, rbody.data(), len)) {
    error_ = "recv failed";
    return false;
  }
  if (!reply->ParseFromString(rbody)) {
    error_ = "parse failed";
    return false;
  }
  if (!reply->error().empty()) {
    error_ = reply->error();
    return false;
  }
  return true;
}

raytpu::Value Client::I64(int64_t v) {
  raytpu::Value out;
  out.set_format("i64");
  out.set_data(std::string(reinterpret_cast<const char*>(&v), 8));
  return out;
}

raytpu::Value Client::F64(double v) {
  raytpu::Value out;
  out.set_format("f64");
  out.set_data(std::string(reinterpret_cast<const char*>(&v), 8));
  return out;
}

raytpu::Value Client::Utf8(const std::string& s) {
  raytpu::Value out;
  out.set_format("utf8");
  out.set_data(s);
  return out;
}

raytpu::Value Client::Raw(const std::string& data) {
  raytpu::Value out;
  out.set_format("raw");
  out.set_data(data);
  return out;
}

std::string Client::Put(const raytpu::Value& value) {
  raytpu::ClientRequest req;
  req.mutable_put()->mutable_value()->CopyFrom(value);
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply)) return "";
  return reply.put().object_id();
}

std::string Client::PutRaw(const std::string& d) { return Put(Raw(d)); }
std::string Client::PutI64(int64_t v) { return Put(I64(v)); }
std::string Client::PutF64(double v) { return Put(F64(v)); }
std::string Client::PutUtf8(const std::string& s) { return Put(Utf8(s)); }

raytpu::Value Client::Get(const std::string& object_id, double timeout_s,
                          bool* found) {
  raytpu::ClientRequest req;
  req.mutable_get()->set_object_id(object_id);
  req.mutable_get()->set_timeout_s(timeout_s);
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply)) {
    if (found) *found = false;
    return raytpu::Value();
  }
  if (found) *found = reply.get().found();
  return reply.get().value();
}

std::vector<std::string> Client::Submit(
    const std::string& fn_name, const std::vector<raytpu::Value>& args,
    int num_returns) {
  raytpu::ClientRequest req;
  auto* sub = req.mutable_submit();
  sub->set_fn_name(fn_name);
  sub->set_num_returns(num_returns);
  for (const auto& a : args) sub->add_args()->mutable_value()->CopyFrom(a);
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply)) return {};
  return {reply.submit().return_ids().begin(),
          reply.submit().return_ids().end()};
}

std::string Client::CreateActor(const std::string& class_name,
                                const std::vector<raytpu::Value>& args,
                                double num_cpus, const std::string& name,
                                const std::string& placement_group_id,
                                int bundle_index) {
  raytpu::ClientRequest req;
  auto* ca = req.mutable_create_actor();
  ca->set_class_name(class_name);
  ca->set_num_cpus(num_cpus);
  if (!name.empty()) ca->set_name(name);
  if (!placement_group_id.empty()) {
    ca->set_placement_group_id(placement_group_id);
    ca->set_bundle_index(bundle_index);
  }
  for (const auto& a : args) ca->add_args()->mutable_value()->CopyFrom(a);
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply)) return "";
  return reply.create_actor().actor_id();
}

std::string Client::CreatePlacementGroup(
    const std::vector<std::map<std::string, double>>& bundles,
    const std::string& strategy, const std::string& name,
    double ready_timeout_s, bool* ready) {
  raytpu::ClientRequest req;
  auto* pg = req.mutable_create_placement_group();
  for (const auto& b : bundles) {
    auto* bundle = pg->add_bundles();
    for (const auto& kv : b) {
      (*bundle->mutable_resources())[kv.first] = kv.second;
    }
  }
  pg->set_strategy(strategy);
  if (!name.empty()) pg->set_name(name);
  pg->set_ready_timeout_s(ready_timeout_s);
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply)) return "";
  if (ready) *ready = reply.create_placement_group().ready();
  return reply.create_placement_group().placement_group_id();
}

bool Client::RemovePlacementGroup(const std::string& placement_group_id) {
  raytpu::ClientRequest req;
  req.mutable_remove_placement_group()->set_placement_group_id(
      placement_group_id);
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply)) return false;
  return reply.remove_placement_group().ok();
}

std::string Client::CallActor(const std::string& actor_id,
                              const std::string& method,
                              const std::vector<raytpu::Value>& args) {
  raytpu::ClientRequest req;
  auto* call = req.mutable_actor_call();
  call->set_actor_id(actor_id);
  call->set_method(method);
  for (const auto& a : args) {
    call->add_args()->mutable_value()->CopyFrom(a);
  }
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply)) return "";
  return reply.actor_call().return_id();
}

bool Client::KillActor(const std::string& actor_id, bool no_restart) {
  raytpu::ClientRequest req;
  req.mutable_kill_actor()->set_actor_id(actor_id);
  req.mutable_kill_actor()->set_no_restart(no_restart);
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply)) return false;
  return reply.kill_actor().ok();
}

bool Client::Wait(const std::vector<std::string>& object_ids,
                  int num_returns, double timeout_s,
                  std::vector<std::string>* ready) {
  raytpu::ClientRequest req;
  auto* w = req.mutable_wait();
  for (const auto& oid : object_ids) w->add_object_ids(oid);
  w->set_num_returns(num_returns);
  w->set_timeout_s(timeout_s);
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply)) return false;
  if (ready) {
    ready->assign(reply.wait().ready().begin(),
                  reply.wait().ready().end());
  }
  return static_cast<int>(reply.wait().ready_size()) >= num_returns;
}

bool Client::KvPut(const std::string& key, const std::string& value) {
  raytpu::ClientRequest req;
  req.mutable_kv_put()->set_key(key);
  req.mutable_kv_put()->set_value(value);
  raytpu::ClientReply reply;
  return Rpc(&req, &reply);
}

bool Client::KvGet(const std::string& key, std::string* value) {
  raytpu::ClientRequest req;
  req.mutable_kv_get()->set_key(key);
  raytpu::ClientReply reply;
  if (!Rpc(&req, &reply) || !reply.kv_get().found()) return false;
  *value = reply.kv_get().value();
  return true;
}

}  // namespace raytpu_client
