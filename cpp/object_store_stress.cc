// Multi-threaded stress harness for the sharded shm object store —
// compiled with -fsanitize=thread and RUN (not just built) by the
// sanitizer tier (tests/test_sanitizers.py; parity: the reference's
// bazel --config=tsan CI actually executing its store tests).
//
// The workload follows the store's usage contract exactly — write only
// between a successful create and the seal, read only between a
// successful get and the release — so every TSan report is a real
// synchronization bug in object_store.cpp (shard mutexes, global extent
// list, lock-free stats/lru-clock), not harness noise. The arena is
// deliberately small: eviction, cross-shard victim sweeps, and the
// global free list all run under contention.
//
//   argv: [n_threads] [iters_per_thread] [arena_mb]

#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>

#include <atomic>
#include <cstdint>
#include <vector>

extern "C" {
int store_init(void* base, uint64_t total_size, uint64_t num_slots,
               uint64_t nshards);
int store_reserve(void* base, uint64_t size, uint64_t* out_offset);
int store_release_extent(void* base, uint64_t abs_offset, uint64_t size);
int store_publish(void* base, const uint8_t* id, uint64_t abs_offset,
                  uint64_t data_size, uint64_t meta_size);
uint64_t store_num_reserves(void* base);
void store_copy_adaptive(void* base, void* dst, const void* src, uint64_t n,
                         int max_threads);
int store_validate(void* base);
int store_create(void* base, const uint8_t* id, uint64_t data_size,
                 uint64_t meta_size, uint64_t* out_offset);
int store_seal(void* base, const uint8_t* id);
int store_get(void* base, const uint8_t* id, uint64_t* out_offset,
              uint64_t* out_data_size, uint64_t* out_meta_size);
int store_release(void* base, const uint8_t* id);
int store_contains(void* base, const uint8_t* id);
int store_delete(void* base, const uint8_t* id);
void store_stats(void* base, uint64_t* out_allocated, uint64_t* out_capacity,
                 uint64_t* out_objects, uint64_t* out_evictions);
}

namespace {

void* g_base = nullptr;
std::atomic<uint64_t> g_errors{0};
std::atomic<uint64_t> g_seals{0};
std::atomic<uint64_t> g_hits{0};
std::atomic<uint64_t> g_reserves{0};
std::atomic<uint64_t> g_publishes{0};

// Object ids are 16 bytes; (tid, slot) keys collide across threads by
// construction: slot is shared modulo space, so create/create races,
// get-while-create and delete-under-get all occur.
void make_id(uint8_t id[16], uint64_t tid, uint64_t slot) {
  memset(id, 0, 16);
  memcpy(id, &slot, 8);
  memcpy(id + 8, &tid, 8);
}

struct Args {
  uint64_t tid;
  uint64_t iters;
  uint64_t nthreads;
};

void* worker(void* argp) {
  Args* a = static_cast<Args*>(argp);
  uint64_t x = a->tid * 2654435761u + 1;  // xorshift-ish per-thread rng
  auto rnd = [&x]() {
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    return x;
  };
  const uint64_t kSlots = 64;  // shared id space across ALL threads
  for (uint64_t i = 0; i < a->iters; i++) {
    uint8_t id[16];
    uint64_t op = rnd() % 10;
    if (op < 5) {  // create -> fill -> seal (own a shared slot)
      make_id(id, rnd() % a->nthreads, rnd() % kSlots);
      // Mix of fastbin-, shard-cache- and global-extent-sized blocks.
      uint64_t sizes[] = {96, 1024, 8192, 70000, 500000};
      uint64_t size = sizes[rnd() % 5];
      uint64_t off = 0;
      int rc = store_create(g_base, id, size, 4, &off);
      if (rc == 0) {
        char* dst = static_cast<char*>(g_base) + off;
        memset(dst, static_cast<int>(i & 0xff), size);
        memcpy(dst + size, "meta", 4);
        if (store_seal(g_base, id) == 0) g_seals.fetch_add(1);
      }
    } else if (op < 8) {  // get -> read -> release
      make_id(id, rnd() % a->nthreads, rnd() % kSlots);
      uint64_t off = 0, dsz = 0, msz = 0;
      if (store_get(g_base, id, &off, &dsz, &msz) == 0) {
        const volatile char* p =
            static_cast<const char*>(g_base) + off;
        uint64_t acc = 0;
        for (uint64_t j = 0; j < dsz; j += 512) acc += p[j];
        (void)acc;
        store_release(g_base, id);
        g_hits.fetch_add(1);
      }
    } else if (op == 8) {
      make_id(id, rnd() % a->nthreads, rnd() % kSlots);
      store_contains(g_base, id);
    } else if (op == 9 && (rnd() & 1)) {
      // Reservation plane: reserve -> bump-carve 1..4 objects -> fill
      // LOCK-FREE (adaptive copy for one of them) -> publish sealed ->
      // release the tail. Interleaves with creates/evictions/deletes on
      // the same shared id space, so every unlock-free fill racing an
      // eviction or a duplicate publish is TSan-visible. Block geometry
      // mirrors _round_block: align64(max(n, 128)).
      const uint64_t kRsv = 192 * 1024;
      uint64_t ext = 0;
      if (store_reserve(g_base, kRsv, &ext) == 0) {
        g_reserves.fetch_add(1);
        uint64_t used = 0;
        uint64_t nobjs = 1 + rnd() % 4;
        char src[4096];
        memset(src, 0x5a, sizeof(src));
        for (uint64_t k = 0; k < nobjs; k++) {
          uint64_t sizes[] = {96, 2048, 40000, 60000};
          uint64_t dsz = sizes[rnd() % 4];
          uint64_t block = dsz + 4 < 128 ? 128 : dsz + 4;
          block = (block + 63) & ~63ULL;
          if (used + block > kRsv) break;
          uint64_t off = ext + used;
          used += block;
          char* dst = static_cast<char*>(g_base) + off;
          // Fill with NO lock held: chunked copies + the adaptive path.
          for (uint64_t w = 0; w < dsz; w += sizeof(src)) {
            uint64_t len = dsz - w < sizeof(src) ? dsz - w : sizeof(src);
            if (w == 0)
              store_copy_adaptive(g_base, dst, src, len, 2);
            else
              memcpy(dst + w, src, len);
          }
          memcpy(dst + dsz, "meta", 4);
          make_id(id, rnd() % a->nthreads, rnd() % kSlots);
          if (store_publish(g_base, id, off, dsz, 4) == 0)
            g_publishes.fetch_add(1);
          else
            // Duplicate id / full table: the chunk stays ours — return
            // it so the accounting balances.
            store_release_extent(g_base, off, block);
        }
        if (used < kRsv)
          store_release_extent(g_base, ext + used, kRsv - used);
      }
    } else {  // delete (refcounted objects survive; sealed idle ones go)
      make_id(id, rnd() % a->nthreads, rnd() % kSlots);
      store_delete(g_base, id);
    }
  }
  return nullptr;
}

}  // namespace

int main(int argc, char** argv) {
  uint64_t nthreads = argc > 1 ? strtoull(argv[1], nullptr, 10) : 8;
  uint64_t iters = argc > 2 ? strtoull(argv[2], nullptr, 10) : 3000;
  uint64_t arena_mb = argc > 3 ? strtoull(argv[3], nullptr, 10) : 48;

  uint64_t total = arena_mb << 20;
  g_base = mmap(nullptr, total, PROT_READ | PROT_WRITE,
                MAP_SHARED | MAP_ANONYMOUS, -1, 0);
  if (g_base == MAP_FAILED) {
    perror("mmap");
    return 2;
  }
  if (store_init(g_base, total, 2048, 4) != 0) {
    fprintf(stderr, "store_init failed\n");
    return 2;
  }
  std::vector<pthread_t> threads(nthreads);
  std::vector<Args> args(nthreads);
  for (uint64_t t = 0; t < nthreads; t++) {
    args[t] = Args{t, iters, nthreads};
    if (pthread_create(&threads[t], nullptr, worker, &args[t]) != 0) {
      fprintf(stderr, "pthread_create failed\n");
      return 2;
    }
  }
  for (uint64_t t = 0; t < nthreads; t++) pthread_join(threads[t], nullptr);

  if (store_validate(g_base) != 0) {
    fprintf(stderr, "store corrupt after stress\n");
    return 1;
  }
  uint64_t allocated = 0, capacity = 0, objects = 0, evictions = 0;
  store_stats(g_base, &allocated, &capacity, &objects, &evictions);
  printf("STRESS_OK threads=%llu iters=%llu seals=%llu hits=%llu "
         "objects=%llu evictions=%llu allocated=%llu reserves=%llu "
         "publishes=%llu\n",
         (unsigned long long)nthreads, (unsigned long long)iters,
         (unsigned long long)g_seals.load(),
         (unsigned long long)g_hits.load(),
         (unsigned long long)objects, (unsigned long long)evictions,
         (unsigned long long)allocated,
         (unsigned long long)g_reserves.load(),
         (unsigned long long)g_publishes.load());
  return 0;
}
