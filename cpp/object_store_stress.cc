// Multi-threaded stress harness for the sharded shm object store —
// compiled with -fsanitize=thread and RUN (not just built) by the
// sanitizer tier (tests/test_sanitizers.py; parity: the reference's
// bazel --config=tsan CI actually executing its store tests).
//
// The workload follows the store's usage contract exactly — write only
// between a successful create and the seal, read only between a
// successful get and the release — so every TSan report is a real
// synchronization bug in object_store.cpp (shard mutexes, global extent
// list, lock-free stats/lru-clock), not harness noise. The arena is
// deliberately small: eviction, cross-shard victim sweeps, and the
// global free list all run under contention.
//
//   argv: [n_threads] [iters_per_thread] [arena_mb]

#include <pthread.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <vector>

extern "C" {
int store_init(void* base, uint64_t total_size, uint64_t num_slots,
               uint64_t nshards);
int store_reserve(void* base, uint64_t size, uint64_t* out_offset);
int store_release_extent(void* base, uint64_t abs_offset, uint64_t size);
int store_publish(void* base, const uint8_t* id, uint64_t abs_offset,
                  uint64_t data_size, uint64_t meta_size);
uint64_t store_num_reserves(void* base);
uint64_t store_rsv_unused(void* base);
int64_t store_reclaim_orphans(void* base);
void store_copy_adaptive(void* base, void* dst, const void* src, uint64_t n,
                         int max_threads);
int store_validate(void* base);
int store_create(void* base, const uint8_t* id, uint64_t data_size,
                 uint64_t meta_size, uint64_t* out_offset);
int store_seal(void* base, const uint8_t* id);
int store_get(void* base, const uint8_t* id, uint64_t* out_offset,
              uint64_t* out_data_size, uint64_t* out_meta_size);
int store_release(void* base, const uint8_t* id);
int store_contains(void* base, const uint8_t* id);
int store_delete(void* base, const uint8_t* id);
void store_stats(void* base, uint64_t* out_allocated, uint64_t* out_capacity,
                 uint64_t* out_objects, uint64_t* out_evictions);
}

namespace {

void* g_base = nullptr;
std::atomic<uint64_t> g_errors{0};
std::atomic<uint64_t> g_seals{0};
std::atomic<uint64_t> g_hits{0};
std::atomic<uint64_t> g_reserves{0};
std::atomic<uint64_t> g_publishes{0};

// Object ids are 16 bytes; (tid, slot) keys collide across threads by
// construction: slot is shared modulo space, so create/create races,
// get-while-create and delete-under-get all occur.
void make_id(uint8_t id[16], uint64_t tid, uint64_t slot) {
  memset(id, 0, 16);
  memcpy(id, &slot, 8);
  memcpy(id + 8, &tid, 8);
}

struct Args {
  uint64_t tid;
  uint64_t iters;
  uint64_t nthreads;
};

void* worker(void* argp) {
  Args* a = static_cast<Args*>(argp);
  uint64_t x = a->tid * 2654435761u + 1;  // xorshift-ish per-thread rng
  auto rnd = [&x]() {
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    return x;
  };
  const uint64_t kSlots = 64;  // shared id space across ALL threads
  for (uint64_t i = 0; i < a->iters; i++) {
    uint8_t id[16];
    uint64_t op = rnd() % 10;
    if (op < 5) {  // create -> fill -> seal (own a shared slot)
      make_id(id, rnd() % a->nthreads, rnd() % kSlots);
      // Mix of fastbin-, shard-cache- and global-extent-sized blocks.
      uint64_t sizes[] = {96, 1024, 8192, 70000, 500000};
      uint64_t size = sizes[rnd() % 5];
      uint64_t off = 0;
      int rc = store_create(g_base, id, size, 4, &off);
      if (rc == 0) {
        char* dst = static_cast<char*>(g_base) + off;
        memset(dst, static_cast<int>(i & 0xff), size);
        memcpy(dst + size, "meta", 4);
        if (store_seal(g_base, id) == 0) g_seals.fetch_add(1);
      }
    } else if (op < 8) {  // get -> read -> release
      make_id(id, rnd() % a->nthreads, rnd() % kSlots);
      uint64_t off = 0, dsz = 0, msz = 0;
      if (store_get(g_base, id, &off, &dsz, &msz) == 0) {
        const volatile char* p =
            static_cast<const char*>(g_base) + off;
        uint64_t acc = 0;
        for (uint64_t j = 0; j < dsz; j += 512) acc += p[j];
        (void)acc;
        store_release(g_base, id);
        g_hits.fetch_add(1);
      }
    } else if (op == 8) {
      make_id(id, rnd() % a->nthreads, rnd() % kSlots);
      store_contains(g_base, id);
    } else if (op == 9 && (rnd() & 1)) {
      // Reservation plane: reserve -> bump-carve 1..4 objects -> fill
      // LOCK-FREE (adaptive copy for one of them) -> publish sealed ->
      // release the tail. Interleaves with creates/evictions/deletes on
      // the same shared id space, so every unlock-free fill racing an
      // eviction or a duplicate publish is TSan-visible. Block geometry
      // mirrors _round_block: align64(max(n, 128)).
      const uint64_t kRsv = 192 * 1024;
      uint64_t ext = 0;
      if (store_reserve(g_base, kRsv, &ext) == 0) {
        g_reserves.fetch_add(1);
        uint64_t used = 0;
        uint64_t nobjs = 1 + rnd() % 4;
        char src[4096];
        memset(src, 0x5a, sizeof(src));
        for (uint64_t k = 0; k < nobjs; k++) {
          uint64_t sizes[] = {96, 2048, 40000, 60000};
          uint64_t dsz = sizes[rnd() % 4];
          uint64_t block = dsz + 4 < 128 ? 128 : dsz + 4;
          block = (block + 63) & ~63ULL;
          if (used + block > kRsv) break;
          uint64_t off = ext + used;
          used += block;
          char* dst = static_cast<char*>(g_base) + off;
          // Fill with NO lock held: chunked copies + the adaptive path.
          for (uint64_t w = 0; w < dsz; w += sizeof(src)) {
            uint64_t len = dsz - w < sizeof(src) ? dsz - w : sizeof(src);
            if (w == 0)
              store_copy_adaptive(g_base, dst, src, len, 2);
            else
              memcpy(dst + w, src, len);
          }
          memcpy(dst + dsz, "meta", 4);
          make_id(id, rnd() % a->nthreads, rnd() % kSlots);
          if (store_publish(g_base, id, off, dsz, 4) == 0)
            g_publishes.fetch_add(1);
          else
            // Duplicate id / full table: the chunk stays ours — return
            // it so the accounting balances.
            store_release_extent(g_base, off, block);
        }
        if (used < kRsv)
          store_release_extent(g_base, ext + used, kRsv - used);
      }
    } else {  // delete (refcounted objects survive; sealed idle ones go)
      make_id(id, rnd() % a->nthreads, rnd() % kSlots);
      store_delete(g_base, id);
    }
  }
  return nullptr;
}

}  // namespace

int main(int argc, char** argv) {
  uint64_t nthreads = argc > 1 ? strtoull(argv[1], nullptr, 10) : 8;
  uint64_t iters = argc > 2 ? strtoull(argv[2], nullptr, 10) : 3000;
  uint64_t arena_mb = argc > 3 ? strtoull(argv[3], nullptr, 10) : 48;

  uint64_t total = arena_mb << 20;
  g_base = mmap(nullptr, total, PROT_READ | PROT_WRITE,
                MAP_SHARED | MAP_ANONYMOUS, -1, 0);
  if (g_base == MAP_FAILED) {
    perror("mmap");
    return 2;
  }
  if (store_init(g_base, total, 2048, 4) != 0) {
    fprintf(stderr, "store_init failed\n");
    return 2;
  }
  std::vector<pthread_t> threads(nthreads);
  std::vector<Args> args(nthreads);
  for (uint64_t t = 0; t < nthreads; t++) {
    args[t] = Args{t, iters, nthreads};
    if (pthread_create(&threads[t], nullptr, worker, &args[t]) != 0) {
      fprintf(stderr, "pthread_create failed\n");
      return 2;
    }
  }
  for (uint64_t t = 0; t < nthreads; t++) pthread_join(threads[t], nullptr);

  if (store_validate(g_base) != 0) {
    fprintf(stderr, "store corrupt after stress\n");
    return 1;
  }

  // Kill-and-reclaim: fork a child that reserves an extent, publishes one
  // object into it, bump-carves a second, then SIGKILLs itself — the
  // crash window between store_reserve and the final store_publish. The
  // parent's pid-liveness sweep must return every unpublished byte and
  // zero rsv_unused, with the published object surviving. The arena is
  // MAP_SHARED, so the child's mutations are visible here (the same
  // crash-consistency contract a SIGKILLed client process exercises).
  uint64_t rsv_before = store_rsv_unused(g_base);
  pid_t child = fork();
  if (child == 0) {
    uint64_t ext = 0;
    const uint64_t kRsv = 256 * 1024;
    if (store_reserve(g_base, kRsv, &ext) == 0) {
      uint8_t id[16];
      make_id(id, 9999, 9999);  // outside the shared (tid, slot) space
      uint64_t dsz = 40000;
      char* dst = static_cast<char*>(g_base) + ext;
      memset(dst, 0x77, dsz + 4);
      store_publish(g_base, id, ext, dsz, 4);
      // Second object carved (cursor advanced client-side) but NEVER
      // published: dies right here with the extent's tail parked.
    }
    kill(getpid(), SIGKILL);
    _exit(3);  // unreachable
  }
  int wst = 0;
  waitpid(child, &wst, 0);
  if (!WIFSIGNALED(wst) || WTERMSIG(wst) != SIGKILL) {
    fprintf(stderr, "kill-and-reclaim child did not die by SIGKILL\n");
    return 1;
  }
  uint64_t rsv_leaked = store_rsv_unused(g_base);
  int64_t reclaimed = store_reclaim_orphans(g_base);
  uint64_t rsv_after = store_rsv_unused(g_base);
  if (reclaimed <= 0 || rsv_after > rsv_before || rsv_leaked <= rsv_before) {
    fprintf(stderr,
            "kill-and-reclaim accounting wrong: before=%llu leaked=%llu "
            "reclaimed=%lld after=%llu\n",
            (unsigned long long)rsv_before, (unsigned long long)rsv_leaked,
            (long long)reclaimed, (unsigned long long)rsv_after);
    return 1;
  }
  {
    uint8_t id[16];
    make_id(id, 9999, 9999);
    uint64_t off = 0, dsz = 0, msz = 0;
    if (store_get(g_base, id, &off, &dsz, &msz) != 0 || dsz != 40000) {
      fprintf(stderr, "published object lost by the reclaim sweep\n");
      return 1;
    }
    store_release(g_base, id);
  }
  if (store_validate(g_base) != 0) {
    fprintf(stderr, "store corrupt after reclaim\n");
    return 1;
  }

  uint64_t allocated = 0, capacity = 0, objects = 0, evictions = 0;
  store_stats(g_base, &allocated, &capacity, &objects, &evictions);
  printf("STRESS_OK threads=%llu iters=%llu seals=%llu hits=%llu "
         "objects=%llu evictions=%llu allocated=%llu reserves=%llu "
         "publishes=%llu reclaimed=%lld\n",
         (unsigned long long)nthreads, (unsigned long long)iters,
         (unsigned long long)g_seals.load(),
         (unsigned long long)g_hits.load(),
         (unsigned long long)objects, (unsigned long long)evictions,
         (unsigned long long)allocated,
         (unsigned long long)g_reserves.load(),
         (unsigned long long)g_publishes.load(),
         (long long)reclaimed);
  return 0;
}
