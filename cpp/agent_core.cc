// Native select-round core for the scheduling hot loop (the raylet-split's
// C++ half). Owns, per agent process:
//
//   * the FRAME PUMP — epoll readiness, MSG_DONTWAIT reads into per-connection
//     buffers, outer-frame splitting (the <Q len><I nbufs>[<Q blen>...] framing
//     of core/transport.py, proto-flag frames included), and a pickle-prefix
//     sniffer that classifies each frame's op without a Python unpickle;
//   * the LEASE LEDGER — the un-started lease queue (raw pickled spec bytes,
//     carried opaque end to end), the (task_id, lease_seq) dedup table that
//     makes head lease re-drives idempotent, per-worker load / sent-fn /
//     eligibility bookkeeping, and the inflight map that worker-death replay
//     drains;
//   * the DISPATCH PLANNER — pops leases onto idle workers depth-K deep and
//     builds the wire frames natively (hand-rolled pickle of the fixed
//     ("reg_fn", fn, blob) / ("exec_raw", spec_bytes) shapes into per-worker
//     outboxes, and the round's ("node_done_raw", whex, [raw frames]) batch
//     toward the head) so the hot loop never pickles or unpickles in Python;
//   * a RESTRICTED UNPICKLER — walks the C-pickler output of the few hot
//     frame shapes (node_exec_raw ingest; done/done_batch task-id extraction)
//     and BAILS to the Python path on any opcode outside its contract, so an
//     unexpected payload is a slow frame, never a wrong one.
//
// Python keeps policy and the actual socket writes: chaos sites, spill
// decisions, worker spawn, and every send happen under the same Python locks
// as the pure-Python path (ray_tpu/core/node_agent.py gates on `native_sched`).
//
// Wire-contract note (tools/staticcheck wire-drift): the outer framing and
// AgentFrame oneof tags used by the proto sniffer below are cross-checked
// against ray_tpu/protocol/raytpu.proto — see kAgentFrameTags.

#include <errno.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---- outer framing (must match core/transport.py) ----
static const uint32_t PROTO_FLAG = 0x80000000u;

// AgentFrame oneof field tags (ray_tpu/protocol/raytpu.proto). The pump
// labels proto-framed control messages by their outermost tag so Python can
// route without a trial decode; staticcheck pins these both ways against the
// .proto. Wire type is always 2 (length-delimited submessage).
struct AgentFrameTag { int field; const char* name; };
static const AgentFrameTag kAgentFrameTags[] = {
    {1, "register_node"}, {2, "heartbeat"}, {3, "node_ack"},
    {4, "worker_death"}, {5, "spawn_worker"}, {6, "kill_worker"},
    {7, "fetch"}, {8, "fetched"}, {9, "free_object"}, {10, "seq_skip"},
    {11, "cluster_view"}, {12, "lease_spilled"}, {13, "task_events"},
    {14, "metrics_update"},
};

// ---- pickle opcodes (protocol 5, CPython C pickler output) ----
enum : uint8_t {
  OP_PROTO = 0x80, OP_FRAME = 0x95, OP_STOP = '.',
  OP_NONE = 'N', OP_NEWTRUE = 0x88, OP_NEWFALSE = 0x89,
  OP_BININT = 'J', OP_BININT1 = 'K', OP_BININT2 = 'M', OP_LONG1 = 0x8a,
  OP_BINFLOAT = 'G',
  OP_SHORT_BINBYTES = 'C', OP_BINBYTES = 'B', OP_BINBYTES8 = 0x8e,
  OP_SHORT_BINUNICODE = 0x8c, OP_BINUNICODE = 'X', OP_BINUNICODE8 = 0x8d,
  OP_EMPTY_LIST = ']', OP_EMPTY_TUPLE = ')', OP_MARK = '(',
  OP_TUPLE1 = 0x85, OP_TUPLE2 = 0x86, OP_TUPLE3 = 0x87, OP_TUPLE = 't',
  OP_APPEND = 'a', OP_APPENDS = 'e',
  OP_MEMOIZE = 0x94, OP_BINGET = 'h', OP_LONG_BINGET = 'j',
  OP_NEXT_BUFFER = 0x97, OP_READONLY_BUFFER = 0x98,
};

struct PVal {
  enum Kind { NONE, BOOL, INT, BYTES, STR, LIST, TUPLE, OPAQUE } kind;
  int64_t i = 0;
  const uint8_t* p = nullptr;  // BYTES/STR view into the frame buffer
  uint64_t len = 0;
  std::vector<int> items;      // LIST/TUPLE arena ids
};

// Restricted pickle walker: builds an arena of PVals (stack holds arena ids
// so memo aliasing — a BINGET of a list later APPENDS-mutated — stays
// correct). Returns the arena id of the root value, or -1 to bail.
struct PickleWalk {
  std::deque<PVal> arena;
  std::vector<int> stack;
  std::vector<int> marks;
  std::vector<int> memo;

  int push(PVal&& v) {
    arena.emplace_back(std::move(v));
    stack.push_back((int)arena.size() - 1);
    return stack.back();
  }

  int parse(const uint8_t* d, uint64_t n) {
    uint64_t i = 0;
    while (i < n) {
      uint8_t op = d[i++];
      switch (op) {
        case OP_PROTO: if (i + 1 > n) return -1; i += 1; break;
        case OP_FRAME: if (i + 8 > n) return -1; i += 8; break;
        case OP_NONE: push({PVal::NONE}); break;
        case OP_NEWTRUE: { PVal v{PVal::BOOL}; v.i = 1; push(std::move(v)); break; }
        case OP_NEWFALSE: { PVal v{PVal::BOOL}; v.i = 0; push(std::move(v)); break; }
        case OP_BININT: {
          if (i + 4 > n) return -1;
          int32_t x; memcpy(&x, d + i, 4); i += 4;
          PVal v{PVal::INT}; v.i = x; push(std::move(v)); break;
        }
        case OP_BININT1: {
          if (i + 1 > n) return -1;
          PVal v{PVal::INT}; v.i = d[i]; i += 1; push(std::move(v)); break;
        }
        case OP_BININT2: {
          if (i + 2 > n) return -1;
          uint16_t x; memcpy(&x, d + i, 2); i += 2;
          PVal v{PVal::INT}; v.i = x; push(std::move(v)); break;
        }
        case OP_LONG1: {
          if (i + 1 > n) return -1;
          uint8_t k = d[i]; i += 1;
          if (i + k > n || k > 8) return -1;
          int64_t x = 0;
          for (int b = 0; b < k; b++) x |= (int64_t)d[i + b] << (8 * b);
          if (k && (d[i + k - 1] & 0x80))  // sign-extend
            for (int b = k; b < 8; b++) x |= (int64_t)0xff << (8 * b);
          i += k;
          PVal v{PVal::INT}; v.i = x; push(std::move(v)); break;
        }
        case OP_BINFLOAT: {
          if (i + 8 > n) return -1; i += 8;
          push({PVal::OPAQUE}); break;
        }
        case OP_SHORT_BINBYTES: case OP_SHORT_BINUNICODE: {
          if (i + 1 > n) return -1;
          uint64_t k = d[i]; i += 1;
          if (i + k > n) return -1;
          PVal v{op == OP_SHORT_BINBYTES ? PVal::BYTES : PVal::STR};
          v.p = d + i; v.len = k; i += k; push(std::move(v)); break;
        }
        case OP_BINBYTES: case OP_BINUNICODE: {
          if (i + 4 > n) return -1;
          uint32_t k; memcpy(&k, d + i, 4); i += 4;
          if (i + k > n) return -1;
          PVal v{op == OP_BINBYTES ? PVal::BYTES : PVal::STR};
          v.p = d + i; v.len = k; i += k; push(std::move(v)); break;
        }
        case OP_BINBYTES8: case OP_BINUNICODE8: {
          if (i + 8 > n) return -1;
          uint64_t k; memcpy(&k, d + i, 8); i += 8;
          if (k > n || i + k > n) return -1;
          PVal v{op == OP_BINBYTES8 ? PVal::BYTES : PVal::STR};
          v.p = d + i; v.len = k; i += k; push(std::move(v)); break;
        }
        case OP_EMPTY_LIST: push({PVal::LIST}); break;
        case OP_EMPTY_TUPLE: push({PVal::TUPLE}); break;
        case OP_MARK: marks.push_back((int)stack.size()); break;
        case OP_APPEND: {
          if (stack.size() < 2) return -1;
          int it = stack.back(); stack.pop_back();
          PVal& l = arena[stack.back()];
          if (l.kind != PVal::LIST) return -1;
          l.items.push_back(it); break;
        }
        case OP_APPENDS: {
          if (marks.empty()) return -1;
          int m = marks.back(); marks.pop_back();
          if ((int)stack.size() < m || m < 1) return -1;
          PVal& l = arena[stack[m - 1]];
          if (l.kind != PVal::LIST) return -1;
          for (int j = m; j < (int)stack.size(); j++) l.items.push_back(stack[j]);
          stack.resize(m); break;
        }
        case OP_TUPLE1: case OP_TUPLE2: case OP_TUPLE3: {
          int k = op - OP_TUPLE1 + 1;
          if ((int)stack.size() < k) return -1;
          PVal v{PVal::TUPLE};
          v.items.assign(stack.end() - k, stack.end());
          stack.resize(stack.size() - k);
          push(std::move(v)); break;
        }
        case OP_TUPLE: {
          if (marks.empty()) return -1;
          int m = marks.back(); marks.pop_back();
          if ((int)stack.size() < m) return -1;
          PVal v{PVal::TUPLE};
          v.items.assign(stack.begin() + m, stack.end());
          stack.resize(m);
          push(std::move(v)); break;
        }
        case OP_MEMOIZE:
          if (stack.empty()) return -1;
          memo.push_back(stack.back()); break;
        case OP_BINGET: {
          if (i + 1 > n) return -1;
          uint8_t k = d[i]; i += 1;
          if (k >= memo.size()) return -1;
          stack.push_back(memo[k]); break;
        }
        case OP_LONG_BINGET: {
          if (i + 4 > n) return -1;
          uint32_t k; memcpy(&k, d + i, 4); i += 4;
          if (k >= memo.size()) return -1;
          stack.push_back(memo[k]); break;
        }
        case OP_NEXT_BUFFER: push({PVal::OPAQUE}); break;
        case OP_READONLY_BUFFER: break;  // wraps top in place
        case OP_STOP:
          if (stack.size() != 1) return -1;
          return stack.back();
        default:
          return -1;  // outside the contract: Python owns this frame
      }
    }
    return -1;
  }
};

// Cheap op sniff: the first string literal pushed in a C-pickled tuple
// ("op", ...) is the op. Returns length of op copied into out (0 = unknown).
static int sniff_op(const uint8_t* d, uint64_t n, char* out, int cap) {
  uint64_t i = 0;
  if (i + 2 <= n && d[i] == OP_PROTO) i += 2;
  if (i + 9 <= n && d[i] == OP_FRAME) i += 9;
  while (i < n && d[i] == OP_MARK) i += 1;  // 4+-tuples open with MARK
  if (i >= n) return 0;
  uint64_t k = 0;
  if (d[i] == OP_SHORT_BINUNICODE) {
    if (i + 2 > n) return 0;
    k = d[i + 1]; i += 2;
  } else if (d[i] == OP_BINUNICODE) {
    if (i + 5 > n) return 0;
    uint32_t kk; memcpy(&kk, d + i + 1, 4); k = kk; i += 5;
  } else {
    return 0;
  }
  if (k == 0 || k >= (uint64_t)cap || i + k > n) return 0;
  memcpy(out, d + i, k);
  out[k] = 0;
  return (int)k;
}

// ---- native pickle writers for the fixed hot-frame shapes ----

static void put_u64(std::string& o, uint64_t v) { o.append((const char*)&v, 8); }
static void put_u32(std::string& o, uint32_t v) { o.append((const char*)&v, 4); }

static void pk_bytes(std::string& o, const uint8_t* p, uint64_t n) {
  if (n < 256) {
    o.push_back((char)OP_SHORT_BINBYTES);
    o.push_back((char)n);
  } else if (n <= 0xffffffffu) {
    o.push_back((char)OP_BINBYTES);
    put_u32(o, (uint32_t)n);
  } else {
    o.push_back((char)OP_BINBYTES8);
    put_u64(o, n);
  }
  o.append((const char*)p, n);
}

static void pk_str(std::string& o, const char* s) {
  size_t n = strlen(s);
  o.push_back((char)OP_SHORT_BINUNICODE);
  o.push_back((char)n);
  o.append(s, n);
}

static void pk_proto(std::string& o) {
  o.push_back((char)OP_PROTO);
  o.push_back((char)5);
}

// One complete outer frame carrying pickled `payload` (no oob buffers).
static void frame_wrap(std::string& out, const std::string& payload) {
  put_u64(out, payload.size());
  put_u32(out, 0);
  out += payload;
}

// ("exec_raw", <spec bytes>) as a complete outer frame.
static void build_exec_raw(std::string& out, const std::string& spec) {
  std::string p;
  pk_proto(p);
  pk_str(p, "exec_raw");
  pk_bytes(p, (const uint8_t*)spec.data(), spec.size());
  p.push_back((char)OP_TUPLE2);
  p.push_back((char)OP_STOP);
  frame_wrap(out, p);
}

// ("reg_fn", <fn bytes>, <blob bytes>) as a complete outer frame.
static void build_reg_fn(std::string& out, const std::string& fn,
                         const std::string& blob) {
  std::string p;
  pk_proto(p);
  pk_str(p, "reg_fn");
  pk_bytes(p, (const uint8_t*)fn.data(), fn.size());
  pk_bytes(p, (const uint8_t*)blob.data(), blob.size());
  p.push_back((char)OP_TUPLE3);
  p.push_back((char)OP_STOP);
  frame_wrap(out, p);
}

// ("node_done_raw", <worker hex str>, [<raw frame bytes>, ...]).
static void build_node_done_raw(std::string& out, const std::string& whex,
                                const std::vector<std::string>& raws) {
  std::string p;
  pk_proto(p);
  pk_str(p, "node_done_raw");
  pk_str(p, whex.c_str());
  p.push_back((char)OP_EMPTY_LIST);
  p.push_back((char)OP_MARK);
  for (const auto& r : raws)
    pk_bytes(p, (const uint8_t*)r.data(), r.size());
  p.push_back((char)OP_APPENDS);
  p.push_back((char)OP_TUPLE3);
  p.push_back((char)OP_STOP);
  frame_wrap(out, p);
}

// ---- context ----

struct Conn {
  int fd = -1;
  uint64_t tag = 0;
  bool raw = false;       // cpp-worker plane: hand chunks to Python unsplit
  bool eof = false;
  std::string buf;        // unconsumed inbound bytes
  size_t scan = 0;        // split cursor into buf
};

struct Frame {
  uint64_t tag;
  int kind;               // 0 pickle, 1 proto, 2 raw chunk, 3 eof
  int proto_tag = 0;      // kind 1: AgentFrame oneof field tag (0 unknown)
  const uint8_t* whole = nullptr;  // full frame incl. outer header
  uint64_t whole_len = 0;
  const uint8_t* payload = nullptr;
  uint64_t payload_len = 0;
  std::vector<std::pair<const uint8_t*, uint64_t>> bufs;
  char op[24] = {0};      // sniffed op ("" = not sniffable)
  bool consumed = false;
};

struct LeaseEntry {
  std::string tid, fn, spec;
  std::string name;     // display name (task-event parity on dispatch)
  int64_t attempt = 0;  // retries consumed (task-event parity)
  uint64_t seq = 0;
};

struct WorkerRec {
  int fd = -1;
  uint64_t tag = 0;
  std::string wid, whex;
  bool eligible = true;
  bool gone = true;
  int load = 0;
  std::unordered_map<std::string, bool> fns;   // fn ids already sent
  std::string outbox, outbox_scratch;          // frames staged for this worker
  std::vector<std::string> nd;                 // raw done frames this round
};

struct Ctx {
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  int ep = -1;
  std::unordered_map<int, Conn> conns;          // fd -> conn
  std::vector<epoll_event> events;
  std::vector<Frame> frames;
  std::deque<LeaseEntry> q;
  std::unordered_map<std::string, std::pair<int, LeaseEntry>> inflight;
  std::unordered_map<std::string, uint64_t> seen;   // tid+seq -> gen
  std::deque<std::string> seen_order;
  std::unordered_map<std::string, std::string> blobs;
  std::vector<WorkerRec> workers;
  std::unordered_map<uint64_t, int> tag2widx;
  // round scratch
  struct DRec { std::string tid, name; int widx; int64_t attempt; };
  std::vector<DRec> drecs;                          // dispatched this round
  std::vector<int> out_widx;                        // workers w/ staged outbox
  std::vector<LeaseEntry> stolen;                   // steal/fail results
  std::string nd_out, nd_scratch;
  uint64_t stat_native_dones = 0, stat_native_grants = 0,
           stat_native_dispatch = 0;
};

struct Lock {
  pthread_mutex_t* m;
  explicit Lock(pthread_mutex_t* mm) : m(mm) { pthread_mutex_lock(m); }
  ~Lock() { pthread_mutex_unlock(m); }
};

static std::string seen_key(const uint8_t* tid, int tlen, uint64_t seq) {
  std::string k((const char*)tid, tlen);
  k.append((const char*)&seq, 8);
  return k;
}

// caller holds mu. True => duplicate (already accepted this grant).
static bool seen_check(Ctx* c, const uint8_t* tid, int tlen, uint64_t seq) {
  std::string k = seen_key(tid, tlen, seq);
  if (c->seen.count(k)) return true;
  c->seen.emplace(k, 1);
  c->seen_order.push_back(std::move(k));
  while (c->seen_order.size() > 8192) {
    c->seen.erase(c->seen_order.front());
    c->seen_order.pop_front();
  }
  return false;
}

// caller holds mu
static void push_lease(Ctx* c, LeaseEntry&& e, bool front) {
  if (front) c->q.emplace_front(std::move(e));
  else c->q.emplace_back(std::move(e));
}

// ---- dispatch planner (caller holds mu) ----
// Mirrors NodeAgent._pump_leases: iterate workers in add order, fill each
// eligible python worker to `depth` outstanding execs, stage reg_fn before
// the first exec that needs it. Frames append to the worker's outbox (the
// same staged-outbox ordering contract as the Python path: a concurrent
// planner's bare exec can never outrun the reg_fn it depends on).
static void plan_dispatch(Ctx* c, int depth, int record) {
  if (c->q.empty()) return;
  for (size_t wi = 0; wi < c->workers.size() && !c->q.empty(); wi++) {
    WorkerRec& w = c->workers[wi];
    if (w.gone || !w.eligible) continue;
    bool staged = false;
    while (!c->q.empty() && w.load < depth) {
      LeaseEntry e = std::move(c->q.front());
      c->q.pop_front();
      if (!e.fn.empty() && !w.fns.count(e.fn)) {
        auto b = c->blobs.find(e.fn);
        if (b != c->blobs.end())
          build_reg_fn(w.outbox, e.fn, b->second);
        w.fns.emplace(e.fn, true);
      }
      build_exec_raw(w.outbox, e.spec);
      w.load++;
      staged = true;
      c->stat_native_dispatch++;
      // Key copied BEFORE the move: emplace's argument evaluation order
      // is unspecified, so `e.tid` as the key expression could read a
      // moved-from entry.
      std::string key = e.tid;
      if (record)
        c->drecs.push_back({key, e.name, (int)wi, e.attempt});
      c->inflight.emplace(std::move(key),
                          std::make_pair((int)wi, std::move(e)));
    }
    if (staged) {
      bool listed = false;
      for (int x : c->out_widx) listed |= (x == (int)wi);
      if (!listed) c->out_widx.push_back((int)wi);
    }
  }
}

}  // namespace

extern "C" {

void* agc_new() {
  Ctx* c = new Ctx();
  c->ep = epoll_create1(EPOLL_CLOEXEC);
  return c;
}

void agc_free(void* h) {
  Ctx* c = (Ctx*)h;
  if (c->ep >= 0) close(c->ep);
  delete c;
}

int agc_add_fd(void* h, int fd, uint64_t tag, int raw_mode) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = fd;
  if (epoll_ctl(c->ep, EPOLL_CTL_ADD, fd, &ev) != 0) return -1;
  Conn& cn = c->conns[fd];
  cn.fd = fd;
  cn.tag = tag;
  cn.raw = raw_mode != 0;
  cn.eof = false;
  cn.buf.clear();
  cn.scan = 0;
  return 0;
}

int agc_del_fd(void* h, int fd) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  epoll_ctl(c->ep, EPOLL_CTL_DEL, fd, nullptr);
  c->conns.erase(fd);
  return 0;
}

// Wait for readiness and drain readable bytes into per-conn buffers.
// Returns the number of conns with new data or EOF (0 on timeout).
int agc_poll(void* h, int timeout_ms) {
  Ctx* c = (Ctx*)h;
  c->events.resize(64);
  int n = epoll_wait(c->ep, c->events.data(), (int)c->events.size(),
                     timeout_ms);
  if (n <= 0) return n;
  Lock l(&c->mu);
  int active = 0;
  char tmp[1 << 18];
  for (int i = 0; i < n; i++) {
    int fd = c->events[i].data.fd;
    auto it = c->conns.find(fd);
    if (it == c->conns.end()) continue;
    Conn& cn = it->second;
    bool got = false;
    for (;;) {
      ssize_t r = recv(fd, tmp, sizeof(tmp), MSG_DONTWAIT);
      if (r > 0) {
        cn.buf.append(tmp, (size_t)r);
        got = true;
        if ((size_t)r < sizeof(tmp)) break;
        continue;
      }
      if (r == 0) {
        cn.eof = true;
        got = true;
        break;
      }
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      cn.eof = true;  // hard error: surface as EOF, Python runs death path
      got = true;
      break;
    }
    if (got) active++;
  }
  return active;
}

// Split buffered bytes into frames (per conn, in order). Raw-mode conns
// yield one kind=2 chunk per round; EOF yields a trailing kind=3 record.
// Frame views stay valid until agc_round_end.
int agc_split(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->frames.clear();
  for (auto& kv : c->conns) {
    Conn& cn = kv.second;
    if (cn.raw) {
      if (cn.scan < cn.buf.size()) {
        Frame f;
        f.tag = cn.tag;
        f.kind = 2;
        f.payload = (const uint8_t*)cn.buf.data() + cn.scan;
        f.payload_len = cn.buf.size() - cn.scan;
        cn.scan = cn.buf.size();
        c->frames.push_back(std::move(f));
      }
    } else {
      const uint8_t* d = (const uint8_t*)cn.buf.data();
      size_t n = cn.buf.size();
      while (cn.scan + 12 <= n) {
        uint64_t plen;
        uint32_t nbufs;
        memcpy(&plen, d + cn.scan, 8);
        memcpy(&nbufs, d + cn.scan + 8, 4);
        Frame f;
        f.tag = cn.tag;
        if (nbufs & PROTO_FLAG) {
          uint64_t total = 12 + plen;
          if (cn.scan + total > n) break;
          f.kind = 1;
          f.whole = d + cn.scan;
          f.whole_len = total;
          f.payload = d + cn.scan + 12;
          f.payload_len = plen;
          // outermost submessage tag of the AgentFrame (varint key)
          if (plen >= 1) {
            uint8_t key = f.payload[0];
            if ((key & 7) == 2) f.proto_tag = key >> 3;
          }
          cn.scan += total;
        } else {
          if (nbufs > 4096) { cn.eof = true; break; }  // corrupt header
          uint64_t lens_end = 12 + 8ull * nbufs;
          if (cn.scan + lens_end > n) break;
          uint64_t total = lens_end + plen;
          std::vector<uint64_t> blens(nbufs);
          for (uint32_t b = 0; b < nbufs; b++) {
            memcpy(&blens[b], d + cn.scan + 12 + 8ull * b, 8);
            total += blens[b];
          }
          if (cn.scan + total > n) break;
          f.kind = 0;
          f.whole = d + cn.scan;
          f.whole_len = total;
          f.payload = d + cn.scan + lens_end;
          f.payload_len = plen;
          uint64_t off = cn.scan + lens_end + plen;
          for (uint32_t b = 0; b < nbufs; b++) {
            f.bufs.emplace_back(d + off, blens[b]);
            off += blens[b];
          }
          sniff_op(f.payload, f.payload_len, f.op, sizeof(f.op));
          cn.scan += total;
        }
        c->frames.push_back(std::move(f));
      }
    }
    if (cn.eof && cn.scan >= cn.buf.size()) {
      Frame f;
      f.tag = cn.tag;
      f.kind = 3;
      c->frames.push_back(std::move(f));
    }
  }
  return (int)c->frames.size();
}

// Natively consume the hot frames in the split set:
//   * node_exec_raw (from the head, tag matching `head_tag`): walk entries
//     (tid, fn, seq, blob|None, spec_bytes), dedup against the seen table,
//     register blobs, queue leases.
//   * done / done_batch from worker-tagged conns whose every task id is in
//     the inflight table: pop them, decrement loads, and stage the RAW frame
//     bytes for the round's node_done_raw batch (zero re-serialization).
// Frames it could not fully claim are left untouched for Python.
// Returns the number of frames consumed.
int agc_consume_hot(void* h, uint64_t head_tag) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  int consumed = 0;
  for (auto& f : c->frames) {
    if (f.kind != 0 || f.consumed) continue;
    if (f.tag == head_tag && strcmp(f.op, "node_exec_raw") == 0) {
      PickleWalk w;
      int root = w.parse(f.payload, f.payload_len);
      if (root < 0) continue;
      PVal& tup = w.arena[root];
      if (tup.kind != PVal::TUPLE || tup.items.size() != 2) continue;
      PVal& lst = w.arena[tup.items[1]];
      if (lst.kind != PVal::LIST) continue;
      bool ok = true;
      for (int id : lst.items) {
        PVal& e = w.arena[id];
        if (e.kind != PVal::TUPLE || e.items.size() < 5 ||
            w.arena[e.items[0]].kind != PVal::BYTES ||
            w.arena[e.items[4]].kind != PVal::BYTES) { ok = false; break; }
      }
      if (!ok) continue;  // Python owns surprising shapes
      for (int id : lst.items) {
        PVal& e = w.arena[id];
        PVal& tid = w.arena[e.items[0]];
        PVal& fn = w.arena[e.items[1]];
        PVal& seqv = w.arena[e.items[2]];
        PVal& blob = w.arena[e.items[3]];
        PVal& spec = w.arena[e.items[4]];
        uint64_t seq = seqv.kind == PVal::INT ? (uint64_t)seqv.i : 0;
        if (fn.kind == PVal::BYTES && blob.kind == PVal::BYTES)
          c->blobs[std::string((const char*)fn.p, fn.len)] =
              std::string((const char*)blob.p, blob.len);
        if (seen_check(c, tid.p, (int)tid.len, seq)) continue;
        LeaseEntry le;
        le.tid.assign((const char*)tid.p, tid.len);
        if (fn.kind == PVal::BYTES)
          le.fn.assign((const char*)fn.p, fn.len);
        le.seq = seq;
        le.spec.assign((const char*)spec.p, spec.len);
        if (e.items.size() > 5) {
          PVal& att = w.arena[e.items[5]];
          if (att.kind == PVal::INT) le.attempt = att.i;
        }
        if (e.items.size() > 6) {
          PVal& nm = w.arena[e.items[6]];
          if (nm.kind == PVal::STR)
            le.name.assign((const char*)nm.p, nm.len);
        }
        push_lease(c, std::move(le), false);
        c->stat_native_grants++;
      }
      f.consumed = true;
      consumed++;
      continue;
    }
    bool is_done = strcmp(f.op, "done") == 0;
    bool is_batch = strcmp(f.op, "done_batch") == 0;
    if (!(is_done || is_batch)) continue;
    auto wit = c->tag2widx.find(f.tag);
    if (wit == c->tag2widx.end()) continue;
    if (!f.bufs.empty()) continue;  // oob buffers: forwardable, but keep
    // the contract simple — Python owns buffer-bearing dones
    PickleWalk w;
    int root = w.parse(f.payload, f.payload_len);
    if (root < 0) continue;
    PVal& tup = w.arena[root];
    if (tup.kind != PVal::TUPLE || tup.items.size() < 2) continue;
    std::vector<std::pair<const uint8_t*, uint64_t>> tids;
    if (is_done) {
      PVal& tid = w.arena[tup.items[1]];
      if (tid.kind != PVal::BYTES) continue;
      tids.emplace_back(tid.p, tid.len);
    } else {
      PVal& lst = w.arena[tup.items[1]];
      if (lst.kind != PVal::LIST || lst.items.empty()) continue;
      bool ok = true;
      for (int id : lst.items) {
        PVal& e = w.arena[id];
        if (e.kind != PVal::TUPLE || e.items.empty() ||
            w.arena[e.items[0]].kind != PVal::BYTES) { ok = false; break; }
        PVal& tid = w.arena[e.items[0]];
        tids.emplace_back(tid.p, tid.len);
      }
      if (!ok) continue;
    }
    bool all_leased = true;
    for (auto& t : tids)
      if (!c->inflight.count(std::string((const char*)t.first, t.second))) {
        all_leased = false;
        break;
      }
    if (!all_leased) continue;  // mixed/head-path batch: Python handles
    WorkerRec& wr = c->workers[wit->second];
    for (auto& t : tids) {
      std::string k((const char*)t.first, t.second);
      auto inf = c->inflight.find(k);
      if (inf != c->inflight.end()) {
        int widx = inf->second.first;
        if (widx >= 0 && widx < (int)c->workers.size()) {
          WorkerRec& lw = c->workers[widx];
          if (lw.load > 0) lw.load--;
        }
        c->inflight.erase(inf);
      }
    }
    wr.nd.emplace_back((const char*)f.whole, f.whole_len);
    c->stat_native_dones += tids.size();
    f.consumed = true;
    consumed++;
  }
  return consumed;
}

// Plan + build dispatch batches. record_dispatch=1 captures (tid, widx)
// pairs for Python's task-event emission. Returns the number of workers
// whose outbox gained frames this call.
int agc_dispatch(void* h, int depth, int record_dispatch) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->out_widx.clear();
  c->drecs.clear();  // per-call records: the caller drains them right
                     // after this returns (racing callers see their own)
  plan_dispatch(c, depth, record_dispatch);
  return (int)c->out_widx.size();
}

int agc_outbox_widx(void* h, int i) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (i < 0 || i >= (int)c->out_widx.size()) return -1;
  return c->out_widx[i];
}

// Swap out a worker's staged outbox; the returned view stays valid until
// the next take for the same worker. Call under the worker's flush lock.
int agc_take_outbox(void* h, int widx, const uint8_t** p, uint64_t* n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (widx < 0 || widx >= (int)c->workers.size()) return -1;
  WorkerRec& w = c->workers[widx];
  w.outbox_scratch.clear();
  std::swap(w.outbox, w.outbox_scratch);
  *p = (const uint8_t*)w.outbox_scratch.data();
  *n = w.outbox_scratch.size();
  return 0;
}

int agc_drec_count(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return (int)c->drecs.size();
}

int agc_drec(void* h, int i, const uint8_t** tid, uint64_t* tlen,
             int* widx, int64_t* attempt, const uint8_t** name,
             uint64_t* nlen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (i < 0 || i >= (int)c->drecs.size()) return -1;
  *tid = (const uint8_t*)c->drecs[i].tid.data();
  *tlen = c->drecs[i].tid.size();
  *widx = c->drecs[i].widx;
  *attempt = c->drecs[i].attempt;
  *name = (const uint8_t*)c->drecs[i].name.data();
  *nlen = c->drecs[i].name.size();
  return 0;
}

// The round's node_done_raw batch toward the head: one frame per worker
// that completed leases this round, concatenated (the head's FrameBuffer
// splits them). View valid until the next take.
int agc_nd_take(void* h, const uint8_t** p, uint64_t* n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->nd_scratch.clear();
  for (auto& w : c->workers) {
    if (w.nd.empty()) continue;
    build_node_done_raw(c->nd_scratch, w.whex, w.nd);
    w.nd.clear();
  }
  *p = (const uint8_t*)c->nd_scratch.data();
  *n = c->nd_scratch.size();
  return (int)c->nd_scratch.size();
}

int agc_frame_count(void* h) {
  Ctx* c = (Ctx*)h;
  return (int)c->frames.size();
}

// out layout: tag, kind, proto_tag, payload ptr/len, whole ptr/len, nbufs,
// consumed flag. Returns 0 ok / -1 bad index.
int agc_frame_info(void* h, int i, uint64_t* tag, int* kind, int* proto_tag,
                   const uint8_t** payload, uint64_t* plen,
                   const uint8_t** whole, uint64_t* wlen, int* nbufs,
                   int* consumed) {
  Ctx* c = (Ctx*)h;
  if (i < 0 || i >= (int)c->frames.size()) return -1;
  Frame& f = c->frames[i];
  *tag = f.tag;
  *kind = f.kind;
  *proto_tag = f.proto_tag;
  *payload = f.payload;
  *plen = f.payload_len;
  *whole = f.whole;
  *wlen = f.whole_len;
  *nbufs = (int)f.bufs.size();
  *consumed = f.consumed ? 1 : 0;
  return 0;
}

int agc_frame_buf(void* h, int i, int j, const uint8_t** p, uint64_t* n) {
  Ctx* c = (Ctx*)h;
  if (i < 0 || i >= (int)c->frames.size()) return -1;
  Frame& f = c->frames[i];
  if (j < 0 || j >= (int)f.bufs.size()) return -1;
  *p = f.bufs[j].first;
  *n = f.bufs[j].second;
  return 0;
}

// End of round: drop consumed bytes from conn buffers and clear the frame
// list (all frame views become invalid).
void agc_round_end(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->frames.clear();
  c->drecs.clear();
  for (auto& kv : c->conns) {
    Conn& cn = kv.second;
    if (cn.scan > 0) {
      cn.buf.erase(0, cn.scan);
      cn.scan = 0;
    }
  }
}

// ---- ledger API ----

int agc_worker_add(void* h, uint64_t tag, int fd, const uint8_t* wid,
                   int wid_len, const char* whex, int eligible) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  WorkerRec w;
  w.fd = fd;
  w.tag = tag;
  w.wid.assign((const char*)wid, wid_len);
  w.whex = whex;
  w.eligible = eligible != 0;
  w.gone = false;
  c->workers.push_back(std::move(w));
  int widx = (int)c->workers.size() - 1;
  c->tag2widx[tag] = widx;
  return widx;
}

void agc_worker_remove(void* h, int widx) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (widx < 0 || widx >= (int)c->workers.size()) return;
  WorkerRec& w = c->workers[widx];
  w.gone = true;
  w.eligible = false;
  c->tag2widx.erase(w.tag);
  w.outbox.clear();
  w.nd.clear();
  w.fns.clear();
}

void agc_worker_eligible(void* h, int widx, int ok) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (widx < 0 || widx >= (int)c->workers.size()) return;
  c->workers[widx].eligible = ok != 0 && !c->workers[widx].gone;
}

void agc_load_add(void* h, int widx, int n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (widx < 0 || widx >= (int)c->workers.size()) return;
  WorkerRec& w = c->workers[widx];
  w.load += n;
  if (w.load < 0) w.load = 0;
}

int agc_worker_load(void* h, int widx) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (widx < 0 || widx >= (int)c->workers.size()) return 0;
  return c->workers[widx].load;
}

// 1 = duplicate grant generation (check AND record).
int agc_seen(void* h, const uint8_t* tid, int tlen, uint64_t seq) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return seen_check(c, tid, tlen, seq) ? 1 : 0;
}

// Queue a lease (spec as raw pickle bytes). Returns 0; dedup is the
// caller's job via agc_seen (the two are separate so the object-form
// node_exec handler can dedup before deciding the cpp/python route).
int agc_push(void* h, const uint8_t* tid, int tlen, const uint8_t* fn,
             int flen, uint64_t seq, const uint8_t* spec, uint64_t slen,
             int64_t attempt, const uint8_t* name, int nlen, int front) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  LeaseEntry e;
  e.tid.assign((const char*)tid, tlen);
  if (fn && flen > 0) e.fn.assign((const char*)fn, flen);
  e.seq = seq;
  e.spec.assign((const char*)spec, slen);
  e.attempt = attempt;
  if (name && nlen > 0) e.name.assign((const char*)name, nlen);
  push_lease(c, std::move(e), front != 0);
  return 0;
}

void agc_fn_blob(void* h, const uint8_t* fn, int flen, const uint8_t* blob,
                 uint64_t blen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->blobs[std::string((const char*)fn, flen)] =
      std::string((const char*)blob, blen);
}

int agc_get_fn_blob(void* h, const uint8_t* fn, int flen, const uint8_t** p,
                    uint64_t* n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  auto it = c->blobs.find(std::string((const char*)fn, flen));
  if (it == c->blobs.end()) return -1;
  *p = (const uint8_t*)it->second.data();
  *n = it->second.size();
  return 0;
}

int agc_has_fn_blob(void* h, const uint8_t* fn, int flen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->blobs.count(std::string((const char*)fn, flen)) ? 1 : 0;
}

uint64_t agc_backlog(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->q.size();
}

uint64_t agc_inflight(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->inflight.size();
}

int agc_idle(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  int idle = 0;
  for (auto& w : c->workers)
    if (!w.gone && w.eligible && w.load == 0) idle++;
  return idle;
}

// Pop one completed lease (slow/Python done path). Returns widx or -1.
int agc_inflight_pop(void* h, const uint8_t* tid, int tlen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  auto it = c->inflight.find(std::string((const char*)tid, tlen));
  if (it == c->inflight.end()) return -1;
  int widx = it->second.first;
  if (widx >= 0 && widx < (int)c->workers.size() &&
      c->workers[widx].load > 0)
    c->workers[widx].load--;
  c->inflight.erase(it);
  return widx;
}

// Steal up to n un-started leases from the queue TAIL (spill/reclaim pop
// newest first, preserving local dispatch order of the oldest entries).
int agc_steal_tail(void* h, int n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->stolen.clear();
  while (n-- > 0 && !c->q.empty()) {
    c->stolen.push_back(std::move(c->q.back()));
    c->q.pop_back();
  }
  return (int)c->stolen.size();
}

// Drain a dead worker's inflight leases into the stolen scratch (worker-
// death replay: Python unpickles these and lease_fails them to the head).
int agc_fail_worker(void* h, int widx) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->stolen.clear();
  for (auto it = c->inflight.begin(); it != c->inflight.end();) {
    if (it->second.first == widx) {
      c->stolen.push_back(std::move(it->second.second));
      it = c->inflight.erase(it);
    } else {
      ++it;
    }
  }
  if (widx >= 0 && widx < (int)c->workers.size())
    c->workers[widx].load = 0;
  return (int)c->stolen.size();
}

int agc_stolen(void* h, int i, const uint8_t** tid, uint64_t* tlen,
               const uint8_t** fn, uint64_t* flen, uint64_t* seq,
               const uint8_t** spec, uint64_t* slen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (i < 0 || i >= (int)c->stolen.size()) return -1;
  LeaseEntry& e = c->stolen[i];
  *tid = (const uint8_t*)e.tid.data();
  *tlen = e.tid.size();
  *fn = (const uint8_t*)e.fn.data();
  *flen = e.fn.size();
  *seq = e.seq;
  *spec = (const uint8_t*)e.spec.data();
  *slen = e.spec.size();
  return 0;
}

void agc_stats(void* h, uint64_t* grants, uint64_t* dones,
               uint64_t* dispatched) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  *grants = c->stat_native_grants;
  *dones = c->stat_native_dones;
  *dispatched = c->stat_native_dispatch;
}

// Number of AgentFrame oneof tags the proto sniffer knows (drift gate).
int agc_proto_tag_count() {
  return (int)(sizeof(kAgentFrameTags) / sizeof(kAgentFrameTags[0]));
}

int agc_proto_tag_entry(int i, int* field, const char** name) {
  if (i < 0 || i >= agc_proto_tag_count()) return -1;
  *field = kAgentFrameTags[i].field;
  *name = kAgentFrameTags[i].name;
  return 0;
}

}  // extern "C"
