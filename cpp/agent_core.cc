// Native select-round core for the AGENT's scheduling hot loop (the
// raylet-split's C++ half; the head's sibling lives in head_core.cc and
// the shared machinery — frame pump, restricted unpickler, native
// pickle writers, AgentFrame tag sniffer — in frame_core.h). Owns, per
// agent process:
//
//   * the FRAME PUMP — framecore::FramePump over the head link and
//     every worker socket (raw mode for cpp workers);
//   * the LEASE LEDGER — the un-started lease queue (raw pickled spec
//     bytes, carried opaque end to end), the (task_id, lease_seq) dedup
//     table that makes head lease re-drives idempotent, per-worker
//     load / sent-fn / eligibility bookkeeping, and the inflight map
//     that worker-death replay drains;
//   * the DISPATCH PLANNER — pops leases onto idle workers depth-K deep
//     and builds the wire frames natively (reg_fn / exec_raw into
//     per-worker outboxes, the round's node_done_raw batch toward the
//     head) so the hot loop never pickles or unpickles in Python.
//
// Python keeps policy and the actual socket writes: chaos sites, spill
// decisions, worker spawn, and every send happen under the same Python
// locks as the pure-Python path (ray_tpu/core/node_agent.py gates on
// `native_sched`).

#include "frame_core.h"

#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

using namespace framecore;

namespace {

struct LeaseEntry {
  std::string tid, fn, spec;
  std::string name;     // display name (task-event parity on dispatch)
  int64_t attempt = 0;  // retries consumed (task-event parity)
  uint64_t seq = 0;
};

struct WorkerRec {
  int fd = -1;
  uint64_t tag = 0;
  std::string wid, whex;
  bool eligible = true;
  bool gone = true;
  int load = 0;
  std::unordered_map<std::string, bool> fns;   // fn ids already sent
  std::string outbox, outbox_scratch;          // frames staged for this worker
  std::vector<std::string> nd;                 // raw done frames this round
};

struct Ctx {
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  FramePump pump;
  std::deque<LeaseEntry> q;
  std::unordered_map<std::string, std::pair<int, LeaseEntry>> inflight;
  std::unordered_map<std::string, uint64_t> seen;   // tid+seq -> gen
  std::deque<std::string> seen_order;
  std::unordered_map<std::string, std::string> blobs;
  std::vector<WorkerRec> workers;
  std::unordered_map<uint64_t, int> tag2widx;
  // round scratch
  struct DRec { std::string tid, name; int widx; int64_t attempt; };
  std::vector<DRec> drecs;                          // dispatched this round
  std::vector<int> out_widx;                        // workers w/ staged outbox
  std::vector<LeaseEntry> stolen;                   // steal/fail results
  std::string nd_scratch;
  uint64_t stat_native_dones = 0, stat_native_grants = 0,
           stat_native_dispatch = 0;
};

static std::string seen_key(const uint8_t* tid, int tlen, uint64_t seq) {
  std::string k((const char*)tid, tlen);
  k.append((const char*)&seq, 8);
  return k;
}

// caller holds mu. True => duplicate (already accepted this grant).
static bool seen_check(Ctx* c, const uint8_t* tid, int tlen, uint64_t seq) {
  std::string k = seen_key(tid, tlen, seq);
  if (c->seen.count(k)) return true;
  c->seen.emplace(k, 1);
  c->seen_order.push_back(std::move(k));
  while (c->seen_order.size() > 8192) {
    c->seen.erase(c->seen_order.front());
    c->seen_order.pop_front();
  }
  return false;
}

// caller holds mu
static void push_lease(Ctx* c, LeaseEntry&& e, bool front) {
  if (front) c->q.emplace_front(std::move(e));
  else c->q.emplace_back(std::move(e));
}

// ---- dispatch planner (caller holds mu) ----
// Mirrors NodeAgent._pump_leases: iterate workers in add order, fill each
// eligible python worker to `depth` outstanding execs, stage reg_fn before
// the first exec that needs it. Frames append to the worker's outbox (the
// same staged-outbox ordering contract as the Python path: a concurrent
// planner's bare exec can never outrun the reg_fn it depends on).
static void plan_dispatch(Ctx* c, int depth, int record) {
  if (c->q.empty()) return;
  for (size_t wi = 0; wi < c->workers.size() && !c->q.empty(); wi++) {
    WorkerRec& w = c->workers[wi];
    if (w.gone || !w.eligible) continue;
    bool staged = false;
    while (!c->q.empty() && w.load < depth) {
      LeaseEntry e = std::move(c->q.front());
      c->q.pop_front();
      if (!e.fn.empty() && !w.fns.count(e.fn)) {
        auto b = c->blobs.find(e.fn);
        if (b != c->blobs.end())
          build_reg_fn(w.outbox, e.fn, b->second);
        w.fns.emplace(e.fn, true);
      }
      build_exec_raw(w.outbox, e.spec);
      w.load++;
      staged = true;
      c->stat_native_dispatch++;
      // Key copied BEFORE the move: emplace's argument evaluation order
      // is unspecified, so `e.tid` as the key expression could read a
      // moved-from entry.
      std::string key = e.tid;
      if (record)
        c->drecs.push_back({key, e.name, (int)wi, e.attempt});
      c->inflight.emplace(std::move(key),
                          std::make_pair((int)wi, std::move(e)));
    }
    if (staged) {
      bool listed = false;
      for (int x : c->out_widx) listed |= (x == (int)wi);
      if (!listed) c->out_widx.push_back((int)wi);
    }
  }
}

}  // namespace

extern "C" {

void* agc_new() {
  Ctx* c = new Ctx();
  c->pump.init();
  return c;
}

void agc_free(void* h) {
  Ctx* c = (Ctx*)h;
  c->pump.close_ep();
  delete c;
}

int agc_add_fd(void* h, int fd, uint64_t tag, int raw_mode) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->pump.add_fd(fd, tag, raw_mode ? CONN_RAW : CONN_PICKLE);
}

int agc_del_fd(void* h, int fd) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->pump.del_fd(fd);
}

// Wait for readiness and drain readable bytes into per-conn buffers.
// Returns the number of conns with new data or EOF (0 on timeout).
int agc_poll(void* h, int timeout_ms) {
  Ctx* c = (Ctx*)h;
  int n = c->pump.wait(timeout_ms);
  if (n <= 0) return n;
  Lock l(&c->mu);
  return c->pump.drain(n);
}

// Split buffered bytes into frames (per conn, in order). Raw-mode conns
// yield one kind=2 chunk per round; EOF yields a trailing kind=3 record.
// Frame views stay valid until agc_round_end.
int agc_split(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->pump.split();
}

// Natively consume the hot frames in the split set:
//   * node_exec_raw (from the head, tag matching `head_tag`): walk entries
//     (tid, fn, seq, blob|None, spec_bytes), dedup against the seen table,
//     register blobs, queue leases.
//   * done / done_batch from worker-tagged conns whose every task id is in
//     the inflight table: pop them, decrement loads, and stage the RAW frame
//     bytes for the round's node_done_raw batch (zero re-serialization).
// Frames it could not fully claim are left untouched for Python.
// Returns the number of frames consumed.
int agc_consume_hot(void* h, uint64_t head_tag) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  int consumed = 0;
  for (auto& f : c->pump.frames) {
    if (f.kind != KIND_PICKLE || f.consumed) continue;
    if (f.tag == head_tag && strcmp(f.op, "node_exec_raw") == 0) {
      PickleWalk w;
      int root = w.parse(f.payload, f.payload_len);
      if (root < 0) continue;
      PVal& tup = w.arena[root];
      if (tup.kind != PVal::TUPLE || tup.items.size() != 2) continue;
      PVal& lst = w.arena[tup.items[1]];
      if (lst.kind != PVal::LIST) continue;
      bool ok = true;
      for (int id : lst.items) {
        PVal& e = w.arena[id];
        if (e.kind != PVal::TUPLE || e.items.size() < 5 ||
            w.arena[e.items[0]].kind != PVal::BYTES ||
            w.arena[e.items[4]].kind != PVal::BYTES) { ok = false; break; }
      }
      if (!ok) continue;  // Python owns surprising shapes
      for (int id : lst.items) {
        PVal& e = w.arena[id];
        PVal& tid = w.arena[e.items[0]];
        PVal& fn = w.arena[e.items[1]];
        PVal& seqv = w.arena[e.items[2]];
        PVal& blob = w.arena[e.items[3]];
        PVal& spec = w.arena[e.items[4]];
        uint64_t seq = seqv.kind == PVal::INT ? (uint64_t)seqv.i : 0;
        if (fn.kind == PVal::BYTES && blob.kind == PVal::BYTES)
          c->blobs[std::string((const char*)fn.p, fn.len)] =
              std::string((const char*)blob.p, blob.len);
        if (seen_check(c, tid.p, (int)tid.len, seq)) continue;
        LeaseEntry le;
        le.tid.assign((const char*)tid.p, tid.len);
        if (fn.kind == PVal::BYTES)
          le.fn.assign((const char*)fn.p, fn.len);
        le.seq = seq;
        le.spec.assign((const char*)spec.p, spec.len);
        if (e.items.size() > 5) {
          PVal& att = w.arena[e.items[5]];
          if (att.kind == PVal::INT) le.attempt = att.i;
        }
        if (e.items.size() > 6) {
          PVal& nm = w.arena[e.items[6]];
          if (nm.kind == PVal::STR)
            le.name.assign((const char*)nm.p, nm.len);
        }
        push_lease(c, std::move(le), false);
        c->stat_native_grants++;
      }
      f.consumed = true;
      consumed++;
      continue;
    }
    bool is_done = strcmp(f.op, "done") == 0;
    bool is_batch = strcmp(f.op, "done_batch") == 0;
    if (!(is_done || is_batch)) continue;
    auto wit = c->tag2widx.find(f.tag);
    if (wit == c->tag2widx.end()) continue;
    if (!f.bufs.empty()) continue;  // oob buffers: forwardable, but keep
    // the contract simple — Python owns buffer-bearing dones
    PickleWalk w;
    int root = w.parse(f.payload, f.payload_len);
    if (root < 0) continue;
    PVal& tup = w.arena[root];
    if (tup.kind != PVal::TUPLE || tup.items.size() < 2) continue;
    std::vector<std::pair<const uint8_t*, uint64_t>> tids;
    if (is_done) {
      PVal& tid = w.arena[tup.items[1]];
      if (tid.kind != PVal::BYTES) continue;
      tids.emplace_back(tid.p, tid.len);
    } else {
      PVal& lst = w.arena[tup.items[1]];
      if (lst.kind != PVal::LIST || lst.items.empty()) continue;
      bool ok = true;
      for (int id : lst.items) {
        PVal& e = w.arena[id];
        if (e.kind != PVal::TUPLE || e.items.empty() ||
            w.arena[e.items[0]].kind != PVal::BYTES) { ok = false; break; }
        PVal& tid = w.arena[e.items[0]];
        tids.emplace_back(tid.p, tid.len);
      }
      if (!ok) continue;
    }
    bool all_leased = true;
    for (auto& t : tids)
      if (!c->inflight.count(std::string((const char*)t.first, t.second))) {
        all_leased = false;
        break;
      }
    if (!all_leased) continue;  // mixed/head-path batch: Python handles
    WorkerRec& wr = c->workers[wit->second];
    for (auto& t : tids) {
      std::string k((const char*)t.first, t.second);
      auto inf = c->inflight.find(k);
      if (inf != c->inflight.end()) {
        int widx = inf->second.first;
        if (widx >= 0 && widx < (int)c->workers.size()) {
          WorkerRec& lw = c->workers[widx];
          if (lw.load > 0) lw.load--;
        }
        c->inflight.erase(inf);
      }
    }
    wr.nd.emplace_back((const char*)f.whole, f.whole_len);
    c->stat_native_dones += tids.size();
    f.consumed = true;
    consumed++;
  }
  return consumed;
}

// Plan + build dispatch batches. record_dispatch=1 captures (tid, widx)
// pairs for Python's task-event emission. Returns the number of workers
// whose outbox gained frames this call.
int agc_dispatch(void* h, int depth, int record_dispatch) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->out_widx.clear();
  c->drecs.clear();  // per-call records: the caller drains them right
                     // after this returns (racing callers see their own)
  plan_dispatch(c, depth, record_dispatch);
  return (int)c->out_widx.size();
}

int agc_outbox_widx(void* h, int i) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (i < 0 || i >= (int)c->out_widx.size()) return -1;
  return c->out_widx[i];
}

// Swap out a worker's staged outbox; the returned view stays valid until
// the next take for the same worker. Call under the worker's flush lock.
int agc_take_outbox(void* h, int widx, const uint8_t** p, uint64_t* n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (widx < 0 || widx >= (int)c->workers.size()) return -1;
  WorkerRec& w = c->workers[widx];
  w.outbox_scratch.clear();
  std::swap(w.outbox, w.outbox_scratch);
  *p = (const uint8_t*)w.outbox_scratch.data();
  *n = w.outbox_scratch.size();
  return 0;
}

int agc_drec_count(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return (int)c->drecs.size();
}

int agc_drec(void* h, int i, const uint8_t** tid, uint64_t* tlen,
             int* widx, int64_t* attempt, const uint8_t** name,
             uint64_t* nlen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (i < 0 || i >= (int)c->drecs.size()) return -1;
  *tid = (const uint8_t*)c->drecs[i].tid.data();
  *tlen = c->drecs[i].tid.size();
  *widx = c->drecs[i].widx;
  *attempt = c->drecs[i].attempt;
  *name = (const uint8_t*)c->drecs[i].name.data();
  *nlen = c->drecs[i].name.size();
  return 0;
}

// The round's node_done_raw batch toward the head: one frame per worker
// that completed leases this round, concatenated (the head's FrameBuffer
// splits them). View valid until the next take.
int agc_nd_take(void* h, const uint8_t** p, uint64_t* n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->nd_scratch.clear();
  for (auto& w : c->workers) {
    if (w.nd.empty()) continue;
    build_node_done_raw(c->nd_scratch, w.whex, w.nd);
    w.nd.clear();
  }
  *p = (const uint8_t*)c->nd_scratch.data();
  *n = c->nd_scratch.size();
  return (int)c->nd_scratch.size();
}

int agc_frame_count(void* h) {
  Ctx* c = (Ctx*)h;
  return (int)c->pump.frames.size();
}

// out layout: tag, kind, proto_tag, payload ptr/len, whole ptr/len, nbufs,
// consumed flag. Returns 0 ok / -1 bad index.
int agc_frame_info(void* h, int i, uint64_t* tag, int* kind, int* proto_tag,
                   const uint8_t** payload, uint64_t* plen,
                   const uint8_t** whole, uint64_t* wlen, int* nbufs,
                   int* consumed) {
  Ctx* c = (Ctx*)h;
  return c->pump.frame_info(i, tag, kind, proto_tag, payload, plen, whole,
                            wlen, nbufs, consumed);
}

int agc_frame_buf(void* h, int i, int j, const uint8_t** p, uint64_t* n) {
  Ctx* c = (Ctx*)h;
  return c->pump.frame_buf(i, j, p, n);
}

// End of round: drop consumed bytes from conn buffers and clear the frame
// list (all frame views become invalid).
void agc_round_end(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->drecs.clear();
  c->pump.round_end();
}

// ---- ledger API ----

int agc_worker_add(void* h, uint64_t tag, int fd, const uint8_t* wid,
                   int wid_len, const char* whex, int eligible) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  WorkerRec w;
  w.fd = fd;
  w.tag = tag;
  w.wid.assign((const char*)wid, wid_len);
  w.whex = whex;
  w.eligible = eligible != 0;
  w.gone = false;
  c->workers.push_back(std::move(w));
  int widx = (int)c->workers.size() - 1;
  c->tag2widx[tag] = widx;
  return widx;
}

void agc_worker_remove(void* h, int widx) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (widx < 0 || widx >= (int)c->workers.size()) return;
  WorkerRec& w = c->workers[widx];
  w.gone = true;
  w.eligible = false;
  c->tag2widx.erase(w.tag);
  w.outbox.clear();
  w.nd.clear();
  w.fns.clear();
}

void agc_worker_eligible(void* h, int widx, int ok) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (widx < 0 || widx >= (int)c->workers.size()) return;
  c->workers[widx].eligible = ok != 0 && !c->workers[widx].gone;
}

void agc_load_add(void* h, int widx, int n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (widx < 0 || widx >= (int)c->workers.size()) return;
  WorkerRec& w = c->workers[widx];
  w.load += n;
  if (w.load < 0) w.load = 0;
}

int agc_worker_load(void* h, int widx) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (widx < 0 || widx >= (int)c->workers.size()) return 0;
  return c->workers[widx].load;
}

// 1 = duplicate grant generation (check AND record).
int agc_seen(void* h, const uint8_t* tid, int tlen, uint64_t seq) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return seen_check(c, tid, tlen, seq) ? 1 : 0;
}

// Queue a lease (spec as raw pickle bytes). Returns 0; dedup is the
// caller's job via agc_seen (the two are separate so the object-form
// node_exec handler can dedup before deciding the cpp/python route).
int agc_push(void* h, const uint8_t* tid, int tlen, const uint8_t* fn,
             int flen, uint64_t seq, const uint8_t* spec, uint64_t slen,
             int64_t attempt, const uint8_t* name, int nlen, int front) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  LeaseEntry e;
  e.tid.assign((const char*)tid, tlen);
  if (fn && flen > 0) e.fn.assign((const char*)fn, flen);
  e.seq = seq;
  e.spec.assign((const char*)spec, slen);
  e.attempt = attempt;
  if (name && nlen > 0) e.name.assign((const char*)name, nlen);
  push_lease(c, std::move(e), front != 0);
  return 0;
}

void agc_fn_blob(void* h, const uint8_t* fn, int flen, const uint8_t* blob,
                 uint64_t blen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->blobs[std::string((const char*)fn, flen)] =
      std::string((const char*)blob, blen);
}

int agc_get_fn_blob(void* h, const uint8_t* fn, int flen, const uint8_t** p,
                    uint64_t* n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  auto it = c->blobs.find(std::string((const char*)fn, flen));
  if (it == c->blobs.end()) return -1;
  *p = (const uint8_t*)it->second.data();
  *n = it->second.size();
  return 0;
}

int agc_has_fn_blob(void* h, const uint8_t* fn, int flen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->blobs.count(std::string((const char*)fn, flen)) ? 1 : 0;
}

uint64_t agc_backlog(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->q.size();
}

uint64_t agc_inflight(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->inflight.size();
}

int agc_idle(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  int idle = 0;
  for (auto& w : c->workers)
    if (!w.gone && w.eligible && w.load == 0) idle++;
  return idle;
}

// Pop one completed lease (slow/Python done path). Returns widx or -1.
int agc_inflight_pop(void* h, const uint8_t* tid, int tlen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  auto it = c->inflight.find(std::string((const char*)tid, tlen));
  if (it == c->inflight.end()) return -1;
  int widx = it->second.first;
  if (widx >= 0 && widx < (int)c->workers.size() &&
      c->workers[widx].load > 0)
    c->workers[widx].load--;
  c->inflight.erase(it);
  return widx;
}

// Steal up to n un-started leases from the queue TAIL (spill/reclaim pop
// newest first, preserving local dispatch order of the oldest entries).
int agc_steal_tail(void* h, int n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->stolen.clear();
  while (n-- > 0 && !c->q.empty()) {
    c->stolen.push_back(std::move(c->q.back()));
    c->q.pop_back();
  }
  return (int)c->stolen.size();
}

// Drain a dead worker's inflight leases into the stolen scratch (worker-
// death replay: Python unpickles these and lease_fails them to the head).
int agc_fail_worker(void* h, int widx) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->stolen.clear();
  for (auto it = c->inflight.begin(); it != c->inflight.end();) {
    if (it->second.first == widx) {
      c->stolen.push_back(std::move(it->second.second));
      it = c->inflight.erase(it);
    } else {
      ++it;
    }
  }
  if (widx >= 0 && widx < (int)c->workers.size())
    c->workers[widx].load = 0;
  return (int)c->stolen.size();
}

int agc_stolen(void* h, int i, const uint8_t** tid, uint64_t* tlen,
               const uint8_t** fn, uint64_t* flen, uint64_t* seq,
               const uint8_t** spec, uint64_t* slen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (i < 0 || i >= (int)c->stolen.size()) return -1;
  LeaseEntry& e = c->stolen[i];
  *tid = (const uint8_t*)e.tid.data();
  *tlen = e.tid.size();
  *fn = (const uint8_t*)e.fn.data();
  *flen = e.fn.size();
  *seq = e.seq;
  *spec = (const uint8_t*)e.spec.data();
  *slen = e.spec.size();
  return 0;
}

void agc_stats(void* h, uint64_t* grants, uint64_t* dones,
               uint64_t* dispatched) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  *grants = c->stat_native_grants;
  *dones = c->stat_native_dones;
  *dispatched = c->stat_native_dispatch;
}

// Number of AgentFrame oneof tags the proto sniffer knows (drift gate).
int agc_proto_tag_count() {
  return agent_frame_tag_count();
}

int agc_proto_tag_entry(int i, int* field, const char** name) {
  return agent_frame_tag_entry(i, field, name);
}

}  // extern "C"
