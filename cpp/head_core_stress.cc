// TSan run-mode storm over the native head core's ledger tables
// (cpp/head_core.cc). Contract-correct multi-threaded use, mirroring the
// head process's real thread roles:
//
//   * a PUMP thread runs the listener's round: hdc_poll / hdc_split /
//     hdc_consume_hot, drains the completion records, hdc_round_end;
//   * GRANTER threads stage grants and take per-node outboxes
//     (hdc_grant_add / hdc_grant_take) the way the scheduler and driver
//     threads do — each granter owns a disjoint node set, the same
//     exclusion the per-conn send lock provides in the runtime;
//   * a FEEDER thread writes hand-built node_done_raw frames into the
//     node socketpairs, racing the pump's in-place parse;
//   * a COLD thread replays hdc_inflight_pop (the lease_fail / reclaim
//     path) and churns extra nodes (hdc_node_add / hdc_node_remove /
//     hdc_grant_drop) mid-storm.
//
// Every operation here is legal concurrent API use, so any TSan report
// is a head_core bug, not a harness artifact. Run with
// TSAN_OPTIONS=halt_on_error=1 (tests/test_sanitizers.py).

#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "frame_core.h"

extern "C" {
void* hdc_new();
void hdc_free(void*);
int hdc_add_fd(void*, int, uint64_t, int);
int hdc_del_fd(void*, int);
int hdc_poll(void*, int);
int hdc_split(void*);
int hdc_consume_hot(void*);
int hdc_rec_count(void*);
int hdc_rec_info(void*, int, int*, int*, const uint8_t**, uint64_t*,
                 const uint8_t**, uint64_t*, int*, int64_t*, double*, int*,
                 int*);
int hdc_rec_out(void*, int, const uint8_t**, uint64_t*, int*,
                const uint8_t**, uint64_t*, int*);
int hdc_recs_take(void*, const uint8_t**, uint64_t*);
void hdc_round_end(void*);
int hdc_node_add(void*, uint64_t);
void hdc_node_remove(void*, int);
void hdc_grant_add(void*, int, const uint8_t*, int, const uint8_t*, int,
                   uint64_t, const uint8_t*, uint64_t, int, const uint8_t*,
                   uint64_t, int64_t, const uint8_t*, int);
int hdc_grant_take(void*, int, const uint8_t**, uint64_t*);
void hdc_grant_drop(void*, int);
int hdc_inflight_pop(void*, const uint8_t*, int);
uint64_t hdc_inflight(void*);
void hdc_stats(void*, uint64_t*, uint64_t*, uint64_t*);
}

namespace {

constexpr int kNodes = 4;
constexpr int kGranters = 2;
constexpr int kTasksPerGranter = 5000;

std::atomic<bool> g_stop{false};
std::atomic<uint64_t> g_granted{0}, g_taken{0}, g_fed{0}, g_drained{0},
    g_cold_pops{0};

void make_tid(uint8_t* out, int granter, int i) {
  memset(out, 0, 16);
  out[0] = (uint8_t)(0x10 + granter);
  memcpy(out + 1, &i, sizeof(i));
}

// ("done", tid, None, [(rid, "shm", None, None)], None) as a complete
// outer frame — the buf-less shape a native agent forwards raw.
void build_done(std::string& out, const uint8_t* tid) {
  using namespace framecore;
  std::string p;
  pk_proto(p);
  p.push_back((char)OP_MARK);
  pk_str(p, "done");
  pk_bytes(p, tid, 16);
  pk_none(p);
  p.push_back((char)OP_EMPTY_LIST);
  p.push_back((char)OP_MARK);
  p.push_back((char)OP_MARK);
  pk_bytes(p, tid, 16);  // rid: reuse the tid bytes
  pk_str(p, "shm");
  pk_none(p);
  pk_none(p);
  p.push_back((char)OP_TUPLE);
  p.push_back((char)OP_APPENDS);
  pk_none(p);
  p.push_back((char)OP_TUPLE);
  p.push_back((char)OP_STOP);
  framecore::frame_wrap(out, p);
}

void granter(void* c, int id, const int* nidx, const int* wfd) {
  uint8_t tid[16], fn[16];
  memset(fn, 0x61 + id, 16);
  std::string spec(200 + id * 11, (char)('A' + id));
  const uint8_t* p;
  uint64_t n;
  int per = kNodes / kGranters;
  for (int i = 0; i < kTasksPerGranter; i++) {
    make_tid(tid, id, i);
    int node = nidx[id * per + (i % per)];
    hdc_grant_add(c, node, tid, 16, fn, 16, 1 + (i % 3),
                  (const uint8_t*)"BLOB", 4, i % 5 == 0,
                  (const uint8_t*)spec.data(), spec.size(), i % 4,
                  (const uint8_t*)"stress", 6);
    g_granted.fetch_add(1, std::memory_order_relaxed);
    if (i % 8 == 0) {
      if (hdc_grant_take(c, node, &p, &n) == 0 && n > 0) {
        g_taken.fetch_add(1, std::memory_order_relaxed);
        // feed the grant frame's tids back as completions
        for (int j = i - (i % 8); j <= i; j++) {
          uint8_t t2[16];
          make_tid(t2, id, j);
          std::string done;
          build_done(done, t2);
          std::string nd;
          std::vector<std::string> raws{done};
          framecore::build_node_done_raw(nd, "aabbccdd", raws);
          ssize_t w = write(wfd[id * per + (i % per)], nd.data(),
                            nd.size());
          if (w > 0) g_fed.fetch_add(1, std::memory_order_relaxed);
        }
      }
    }
  }
}

void pump(void* c) {
  while (!g_stop.load(std::memory_order_acquire)) {
    int n = hdc_poll(c, 10);
    if (n < 0) continue;
    hdc_split(c);
    hdc_consume_hot(c);
    // the listener's bulk drain first (what the runtime actually uses),
    // then the per-record accessors over the same round
    const uint8_t* bp;
    uint64_t bn;
    hdc_recs_take(c, &bp, &bn);
    int recs = hdc_rec_count(c);
    int nidx, known, tevp, ooff, nouts;
    int64_t teva;
    double tev4[4];
    const uint8_t *tp, *wp, *rp, *pp;
    uint64_t tl, wl, rl, pl;
    int st, pnone;
    for (int i = 0; i < recs; i++) {
      if (hdc_rec_info(c, i, &nidx, &known, &tp, &tl, &wp, &wl, &tevp,
                       &teva, tev4, &ooff, &nouts) != 0)
        continue;
      for (int j = ooff; j < ooff + nouts; j++)
        hdc_rec_out(c, j, &rp, &rl, &st, &pp, &pl, &pnone);
      g_drained.fetch_add(1, std::memory_order_relaxed);
    }
    hdc_round_end(c);
  }
}

void cold(void* c) {
  uint8_t tid[16];
  uint64_t churn_tag = 9000;
  while (!g_stop.load(std::memory_order_acquire)) {
    for (int g = 0; g < kGranters; g++) {
      for (int i = 0; i < kTasksPerGranter; i += 13) {
        make_tid(tid, g, i);
        if (hdc_inflight_pop(c, tid, 16) >= 0)
          g_cold_pops.fetch_add(1, std::memory_order_relaxed);
      }
    }
    // node churn: a short-lived node gets grants staged, dropped, and
    // retired (the node-death path)
    int n = hdc_node_add(c, churn_tag++);
    hdc_grant_add(c, n, tid, 16, nullptr, 0, 1, nullptr, 0, 0,
                  (const uint8_t*)"spec", 4, 0, nullptr, 0);
    hdc_grant_drop(c, n);
    hdc_node_remove(c, n);
    std::this_thread::yield();
  }
}

}  // namespace

int main() {
  void* c = hdc_new();
  int nidx[kNodes], wfd[kNodes];
  for (int i = 0; i < kNodes; i++) {
    int sp[2];
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, sp) != 0) return 3;
    uint64_t tag = 100 + i;
    hdc_add_fd(c, sp[0], tag, 0);
    nidx[i] = hdc_node_add(c, tag);
    wfd[i] = sp[1];
  }
  std::vector<std::thread> ts;
  ts.emplace_back(pump, c);
  ts.emplace_back(cold, c);
  std::vector<std::thread> gs;
  for (int i = 0; i < kGranters; i++)
    gs.emplace_back(granter, c, i, nidx, wfd);
  for (auto& t : gs) t.join();
  // let the pump drain the tail
  for (int spin = 0; spin < 100 && g_drained.load() < g_fed.load() / 2;
       spin++)
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  g_stop.store(true, std::memory_order_release);
  for (auto& t : ts) t.join();
  uint64_t grants, dones, frames;
  hdc_stats(c, &grants, &dones, &frames);
  printf("granted=%llu taken=%llu fed=%llu drained=%llu cold_pops=%llu "
         "ledger_grants=%llu ledger_dones=%llu frames=%llu inflight=%llu\n",
         (unsigned long long)g_granted.load(),
         (unsigned long long)g_taken.load(),
         (unsigned long long)g_fed.load(),
         (unsigned long long)g_drained.load(),
         (unsigned long long)g_cold_pops.load(),
         (unsigned long long)grants, (unsigned long long)dones,
         (unsigned long long)frames, (unsigned long long)hdc_inflight(c));
  bool ok = g_granted.load() > 0 && g_taken.load() > 0 && dones > 0
            && g_drained.load() > 0 && g_cold_pops.load() > 0;
  hdc_free(c);
  if (!ok) {
    fprintf(stderr, "stress exercised too little of the head ledger\n");
    return 2;
  }
  printf("HEAD_CORE_STRESS_OK\n");
  return 0;
}
