// Native select-round core for the HEAD's scheduling hot loop — the
// second half of the raylet split whose agent side is agent_core.cc
// (shared machinery in frame_core.h). Owns, per head process:
//
//   * the NODE-LISTENER FRAME PUMP — framecore::FramePump over every
//     node-agent TCP link, head-local worker socket and the cluster's
//     accept socket (accept readiness surfaces as a KIND_ACCEPT record;
//     Python runs accept() and registers the new conn);
//   * the COMPLETION LEDGER — in-place `node_done_raw` parse (outer
//     tuple, each forwarded raw worker frame, the done/done_batch
//     payloads inside) into flat completion records, plus the
//     (task_id, lease_seq) per-node inflight table that makes lease
//     re-drives idempotent from the head side too: a grant records the
//     pair, a completion pops it, and a duplicate completion (redrive
//     raced the original) surfaces with known=0 so Python's
//     authoritative pop stays the single decider;
//   * the GRANT BUILDER — native `node_exec_raw` frame builds from raw
//     spec bytes into per-node double-buffered outboxes (the head never
//     re-pickles the grant batch; the spec payload was pickled exactly
//     once by encode_payload).
//
// Python keeps all policy (placement, spill decisions, placement
// groups, dep gating, retries) and every cold path keeps its
// object-form frames (`lease_return` / `lease_spilled` / reclaim /
// cpp-language leases / the lease-redrive watchdog). Chaos-armed
// processes keep this ledger but route every send through per-frame
// send_msg and skip native consumption, so all seeded sites fire
// exactly as in the pure-Python loop (ray_tpu/core/runtime.py gates on
// `native_head`).

#include "frame_core.h"

#include <string>
#include <unordered_map>
#include <vector>

using namespace framecore;

namespace {

struct OutRec {
  const uint8_t* rid = nullptr;
  uint64_t rid_len = 0;
  int status = 0;  // 0 inline, 1 err, 2 location (e.g. "shm")
  const uint8_t* payload = nullptr;
  uint64_t plen = 0;
  int payload_none = 0;
};

struct DoneRec {
  int nidx = -1;            // node conn the frame arrived on
  int known = 0;            // popped a live inflight entry
  const uint8_t* tid = nullptr;
  uint64_t tlen = 0;
  const uint8_t* whex = nullptr;  // executing worker hex (outer tuple)
  uint64_t wlen = 0;
  int tev_present = 0;
  int64_t tev_attempt = 0;
  double tev[4] = {0, 0, 0, 0};   // exec_start, args_ready, exec_done, ts
  int outs_off = 0;
  int n_outs = 0;
};

struct NodeRec {
  uint64_t tag = 0;
  bool gone = true;
  std::string entries;      // staged grant-entry pickles (no list header)
  uint64_t n_entries = 0;
  std::string outbox, outbox_scratch;  // double-buffered grant frames
};

struct Ctx {
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  FramePump pump;
  std::vector<NodeRec> nodes;
  std::unordered_map<uint64_t, int> tag2nidx;
  // task_id -> (nidx, lease_seq): the head-side grant ledger.
  std::unordered_map<std::string, std::pair<int, uint64_t>> inflight;
  // round scratch (views die at hdc_round_end)
  std::vector<DoneRec> recs;
  std::vector<OutRec> outs_pool;
  std::string rec_pack;  // bulk-drain scratch (hdc_recs_take)
  uint64_t stat_grants = 0, stat_dones = 0, stat_frames = 0;
};

// ---- node_done_raw walk (caller holds mu) ----

// Parse ONE forwarded raw worker frame (complete outer frame bytes) into
// staged records. Returns false to bail the whole node_done_raw frame to
// Python (oob buffers, foreign shapes — a bail is a slow frame, never a
// wrong one).
static bool walk_raw_done(int nidx, const uint8_t* whex,
                          uint64_t wlen, const uint8_t* raw, uint64_t rn,
                          std::vector<DoneRec>* recs,
                          std::vector<OutRec>* outs_pool) {
  if (rn < 12) return false;
  uint64_t plen;
  uint32_t nbufs;
  memcpy(&plen, raw, 8);
  memcpy(&nbufs, raw + 8, 4);
  if (nbufs != 0) return false;  // proto-flag or oob buffers: Python owns
  if (12 + plen != rn) return false;
  PickleWalk w;
  int root = w.parse(raw + 12, plen);
  if (root < 0) return false;
  PVal& tup = w.arena[root];
  if (tup.kind != PVal::TUPLE || tup.items.size() < 2) return false;
  PVal& opv = w.arena[tup.items[0]];
  if (opv.kind != PVal::STR) return false;
  std::string op((const char*)opv.p, opv.len);

  // One completion entry: (tid, actor_id, outs[, tev]) with the leading
  // "done" op already stripped for the single-done case.
  auto walk_entry = [&](const std::vector<int>& items, int base) -> bool {
    if ((int)items.size() < base + 3) return false;
    PVal& tid = w.arena[items[base]];
    PVal& actor = w.arena[items[base + 1]];
    PVal& outs = w.arena[items[base + 2]];
    if (tid.kind != PVal::BYTES) return false;
    if (actor.kind != PVal::NONE) return false;  // actor dones: head path
    if (outs.kind != PVal::LIST) return false;
    DoneRec r;
    r.nidx = nidx;
    r.tid = tid.p;
    r.tlen = tid.len;
    r.whex = whex;
    r.wlen = wlen;
    r.outs_off = (int)outs_pool->size();
    for (int oid : outs.items) {
      PVal& e = w.arena[oid];
      if (e.kind != PVal::TUPLE || e.items.size() != 4) return false;
      PVal& rid = w.arena[e.items[0]];
      PVal& st = w.arena[e.items[1]];
      PVal& pay = w.arena[e.items[2]];
      PVal& bufs = w.arena[e.items[3]];
      if (rid.kind != PVal::BYTES || st.kind != PVal::STR) return false;
      if (!(bufs.kind == PVal::NONE
            || (bufs.kind == PVal::LIST && bufs.items.empty())))
        return false;  // in-band buffer lists: Python owns
      OutRec o;
      o.rid = rid.p;
      o.rid_len = rid.len;
      if (st.len == 6 && memcmp(st.p, "inline", 6) == 0) o.status = 0;
      else if (st.len == 3 && memcmp(st.p, "err", 3) == 0) o.status = 1;
      else o.status = 2;
      if (pay.kind == PVal::BYTES) {
        o.payload = pay.p;
        o.plen = pay.len;
      } else if (pay.kind == PVal::NONE) {
        o.payload_none = 1;
      } else {
        return false;
      }
      outs_pool->push_back(o);
      r.n_outs++;
    }
    if ((int)items.size() > base + 3) {
      PVal& tev = w.arena[items[base + 3]];
      if (tev.kind == PVal::TUPLE) {
        if (tev.items.size() != 5) return false;
        PVal& att = w.arena[tev.items[0]];
        if (att.kind != PVal::INT) return false;
        r.tev_attempt = att.i;
        for (int k = 0; k < 4; k++) {
          PVal& v = w.arena[tev.items[k + 1]];
          if (v.kind == PVal::FLOAT) r.tev[k] = v.f;
          else if (v.kind == PVal::INT) r.tev[k] = (double)v.i;
          else return false;
        }
        r.tev_present = 1;
      } else if (tev.kind != PVal::NONE) {
        return false;
      }
    }
    recs->push_back(r);
    return true;
  };

  if (op == "done") {
    return walk_entry(tup.items, 1);
  }
  if (op == "done_batch") {
    PVal& lst = w.arena[tup.items[1]];
    if (lst.kind != PVal::LIST) return false;
    for (int id : lst.items) {
      PVal& e = w.arena[id];
      if (e.kind != PVal::TUPLE) return false;
      if (!walk_entry(e.items, 0)) return false;
    }
    return true;
  }
  return false;
}

}  // namespace

extern "C" {

void* hdc_new() {
  Ctx* c = new Ctx();
  c->pump.init();
  return c;
}

void hdc_free(void* h) {
  Ctx* c = (Ctx*)h;
  c->pump.close_ep();
  delete c;
}

// mode: 0 = pickle-framed conn (nodes, workers, clients), 2 = accept
// socket (readiness only; Python runs accept()).
int hdc_add_fd(void* h, int fd, uint64_t tag, int mode) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->pump.add_fd(fd, tag, mode);
}

int hdc_del_fd(void* h, int fd) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->pump.del_fd(fd);
}

int hdc_poll(void* h, int timeout_ms) {
  Ctx* c = (Ctx*)h;
  int n = c->pump.wait(timeout_ms);
  if (n <= 0) return n;
  Lock l(&c->mu);
  return c->pump.drain(n);
}

int hdc_split(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->pump.split();
}

int hdc_frame_count(void* h) {
  Ctx* c = (Ctx*)h;
  return (int)c->pump.frames.size();
}

int hdc_frame_info(void* h, int i, uint64_t* tag, int* kind, int* proto_tag,
                   const uint8_t** payload, uint64_t* plen,
                   const uint8_t** whole, uint64_t* wlen, int* nbufs,
                   int* consumed) {
  Ctx* c = (Ctx*)h;
  return c->pump.frame_info(i, tag, kind, proto_tag, payload, plen, whole,
                            wlen, nbufs, consumed);
}

int hdc_frame_buf(void* h, int i, int j, const uint8_t** p, uint64_t* n) {
  Ctx* c = (Ctx*)h;
  return c->pump.frame_buf(i, j, p, n);
}

void hdc_round_end(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  c->recs.clear();
  c->outs_pool.clear();
  c->pump.round_end();
}

// ---- node ledger ----

int hdc_node_add(void* h, uint64_t tag) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  NodeRec n;
  n.tag = tag;
  n.gone = false;
  c->nodes.push_back(std::move(n));
  int nidx = (int)c->nodes.size() - 1;
  c->tag2nidx[tag] = nidx;
  return nidx;
}

void hdc_node_remove(void* h, int nidx) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (nidx < 0 || nidx >= (int)c->nodes.size()) return;
  NodeRec& n = c->nodes[nidx];
  n.gone = true;
  c->tag2nidx.erase(n.tag);
  n.entries.clear();
  n.n_entries = 0;
  n.outbox.clear();
  // Python requeues the dead node's leases itself (node.leases is the
  // authoritative table); drop the native mirror so re-grants re-record.
  for (auto it = c->inflight.begin(); it != c->inflight.end();) {
    if (it->second.first == nidx) it = c->inflight.erase(it);
    else ++it;
  }
}

// ---- grant builder ----

// Stage one grant entry for `nidx` and record (tid, seq) inflight. The
// entry pickles to the same 7-tuple the Python grant path ships:
// (task_id, fn_id|None, lease_seq, blob|None, spec_bytes, attempt,
// name|None). Re-staging an inflight (tid, seq) — a lease re-drive —
// updates the ledger in place (idempotent), never duplicates it.
void hdc_grant_add(void* h, int nidx, const uint8_t* tid, int tlen,
                   const uint8_t* fn, int flen, uint64_t seq,
                   const uint8_t* blob, uint64_t blen, int has_blob,
                   const uint8_t* spec, uint64_t slen, int64_t attempt,
                   const uint8_t* name, int nlen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (nidx < 0 || nidx >= (int)c->nodes.size()) return;
  NodeRec& n = c->nodes[nidx];
  if (n.gone) return;
  std::string& o = n.entries;
  o.push_back((char)OP_MARK);
  pk_bytes(o, tid, tlen);
  if (fn && flen > 0) pk_bytes(o, fn, flen);
  else pk_none(o);
  pk_int(o, (int64_t)seq);
  if (has_blob) pk_bytes(o, blob, blen);
  else pk_none(o);
  pk_bytes(o, spec, slen);
  pk_int(o, attempt);
  if (name && nlen > 0) pk_strn(o, name, nlen);
  else pk_none(o);
  o.push_back((char)OP_TUPLE);
  n.n_entries++;
  std::string k((const char*)tid, tlen);
  c->inflight[std::move(k)] = {nidx, seq};
  c->stat_grants++;
}

// Swap out the staged grant batch as ONE complete node_exec_raw outer
// frame. View valid until the next take for the same node. Call under
// the node conn's send lock (the same per-destination write ordering as
// the Python path).
int hdc_grant_take(void* h, int nidx, const uint8_t** p, uint64_t* n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  *p = nullptr;
  *n = 0;
  if (nidx < 0 || nidx >= (int)c->nodes.size()) return -1;
  NodeRec& nd = c->nodes[nidx];
  nd.outbox_scratch.clear();
  if (!nd.n_entries) return 0;
  std::string payload;
  pk_proto(payload);
  pk_str(payload, "node_exec_raw");
  payload.push_back((char)OP_EMPTY_LIST);
  payload.push_back((char)OP_MARK);
  payload += nd.entries;
  payload.push_back((char)OP_APPENDS);
  payload.push_back((char)OP_TUPLE2);
  payload.push_back((char)OP_STOP);
  frame_wrap(nd.outbox_scratch, payload);
  nd.entries.clear();
  nd.n_entries = 0;
  *p = (const uint8_t*)nd.outbox_scratch.data();
  *n = nd.outbox_scratch.size();
  return 0;
}

// Drop a node's staged-but-untaken grants (send failed before take; the
// node-death path requeues the leases from Python's tables).
void hdc_grant_drop(void* h, int nidx) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (nidx < 0 || nidx >= (int)c->nodes.size()) return;
  c->nodes[nidx].entries.clear();
  c->nodes[nidx].n_entries = 0;
}

// ---- completion ledger ----

// Natively consume every node_done_raw frame in the split set arriving
// on a registered node conn: parse outer tuple + each forwarded raw
// worker frame in place, pop the inflight ledger, and stage flat
// completion records for Python's policy pass. A frame with ANY
// surprising shape is left untouched for the Python path. Returns the
// number of frames consumed.
int hdc_consume_hot(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  int consumed = 0;
  for (auto& f : c->pump.frames) {
    if (f.kind != KIND_PICKLE || f.consumed) continue;
    if (strcmp(f.op, "node_done_raw") != 0) continue;
    auto nit = c->tag2nidx.find(f.tag);
    if (nit == c->tag2nidx.end()) continue;  // not a registered node
    if (!f.bufs.empty()) continue;
    int nidx = nit->second;
    PickleWalk w;
    int root = w.parse(f.payload, f.payload_len);
    if (root < 0) continue;
    PVal& tup = w.arena[root];
    if (tup.kind != PVal::TUPLE || tup.items.size() != 3) continue;
    PVal& whex = w.arena[tup.items[1]];
    PVal& raws = w.arena[tup.items[2]];
    if (whex.kind != PVal::STR || raws.kind != PVal::LIST) continue;
    // Two-phase: validate + stage into scratch, commit only when the
    // WHOLE frame parses (a half-consumed frame would double-handle).
    std::vector<DoneRec> recs;
    std::vector<OutRec> outs;
    bool ok = true;
    for (int rid : raws.items) {
      PVal& raw = w.arena[rid];
      if (raw.kind != PVal::BYTES
          || !walk_raw_done(nidx, whex.p, whex.len, raw.p, raw.len,
                            &recs, &outs)) {
        ok = false;
        break;
      }
    }
    if (!ok) continue;
    int out_base = (int)c->outs_pool.size();
    for (auto& r : recs) {
      std::string k((const char*)r.tid, r.tlen);
      auto inf = c->inflight.find(k);
      if (inf != c->inflight.end()) {
        r.known = 1;
        c->inflight.erase(inf);
      }
      r.outs_off += out_base;
      c->recs.push_back(r);
      c->stat_dones++;
    }
    c->outs_pool.insert(c->outs_pool.end(), outs.begin(), outs.end());
    f.consumed = true;
    consumed++;
    c->stat_frames++;
  }
  return consumed;
}

int hdc_rec_count(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return (int)c->recs.size();
}

// Bulk drain: every staged completion record packed into ONE buffer so
// Python reads the round with a single ctypes call + struct unpacks
// (the per-field accessor chatter measurably hit the 16-agent storm).
// Little-endian layout per record:
//   <i nidx><B known><B tev_present><H tlen><H wlen><q tev_attempt>
//   <4d tev><H n_outs> tid whex
//   then per out: <B status><B payload_none><I rid_len><Q plen>
//                 rid payload
// View valid until the next take / hdc_round_end.
int hdc_recs_take(void* h, const uint8_t** p, uint64_t* n) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  std::string& o = c->rec_pack;
  o.clear();
  for (auto& r : c->recs) {
    int32_t nidx = r.nidx;
    o.append((const char*)&nidx, 4);
    o.push_back((char)(r.known ? 1 : 0));
    o.push_back((char)(r.tev_present ? 1 : 0));
    uint16_t tlen = (uint16_t)r.tlen, wlen = (uint16_t)r.wlen;
    o.append((const char*)&tlen, 2);
    o.append((const char*)&wlen, 2);
    o.append((const char*)&r.tev_attempt, 8);
    o.append((const char*)r.tev, 32);
    uint16_t nouts = (uint16_t)r.n_outs;
    o.append((const char*)&nouts, 2);
    o.append((const char*)r.tid, r.tlen);
    o.append((const char*)r.whex, r.wlen);
    for (int j = r.outs_off; j < r.outs_off + r.n_outs; j++) {
      OutRec& e = c->outs_pool[j];
      o.push_back((char)e.status);
      o.push_back((char)e.payload_none);
      uint32_t rl = (uint32_t)e.rid_len;
      o.append((const char*)&rl, 4);
      uint64_t pl = e.plen;
      o.append((const char*)&pl, 8);
      o.append((const char*)e.rid, e.rid_len);
      if (!e.payload_none) o.append((const char*)e.payload, e.plen);
    }
  }
  *p = (const uint8_t*)o.data();
  *n = o.size();
  return (int)c->recs.size();
}

int hdc_rec_info(void* h, int i, int* nidx, int* known,
                 const uint8_t** tid, uint64_t* tlen,
                 const uint8_t** whex, uint64_t* wlen, int* tev_present,
                 int64_t* tev_attempt, double* tev4, int* outs_off,
                 int* n_outs) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (i < 0 || i >= (int)c->recs.size()) return -1;
  DoneRec& r = c->recs[i];
  *nidx = r.nidx;
  *known = r.known;
  *tid = r.tid;
  *tlen = r.tlen;
  *whex = r.whex;
  *wlen = r.wlen;
  *tev_present = r.tev_present;
  *tev_attempt = r.tev_attempt;
  for (int k = 0; k < 4; k++) tev4[k] = r.tev[k];
  *outs_off = r.outs_off;
  *n_outs = r.n_outs;
  return 0;
}

int hdc_rec_out(void* h, int j, const uint8_t** rid, uint64_t* rlen,
                int* status, const uint8_t** payload, uint64_t* plen,
                int* payload_none) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  if (j < 0 || j >= (int)c->outs_pool.size()) return -1;
  OutRec& o = c->outs_pool[j];
  *rid = o.rid;
  *rlen = o.rid_len;
  *status = o.status;
  *payload = o.payload;
  *plen = o.plen;
  *payload_none = o.payload_none;
  return 0;
}

// Cold-path pop (lease_fail / lease_return / reclaim / node death /
// Python-path completion): idempotent, returns the granted nidx or -1.
int hdc_inflight_pop(void* h, const uint8_t* tid, int tlen) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  auto it = c->inflight.find(std::string((const char*)tid, tlen));
  if (it == c->inflight.end()) return -1;
  int nidx = it->second.first;
  c->inflight.erase(it);
  return nidx;
}

uint64_t hdc_inflight(void* h) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  return c->inflight.size();
}

void hdc_stats(void* h, uint64_t* grants, uint64_t* dones,
               uint64_t* frames) {
  Ctx* c = (Ctx*)h;
  Lock l(&c->mu);
  *grants = c->stat_grants;
  *dones = c->stat_dones;
  *frames = c->stat_frames;
}

// The shared AgentFrame oneof tag table (frame_core.h) — the drift gate
// reads it through this core too, so both .so's provably compile the
// same pin.
int hdc_proto_tag_count() {
  return agent_frame_tag_count();
}

int hdc_proto_tag_entry(int i, int* field, const char** name) {
  return agent_frame_tag_entry(i, field, name);
}

}  // extern "C"
