// Minimal C++ frontend for ray_tpu (parity: the reference's standalone C++
// API, cpp/include/ray/api.h — Init/Put/Get/Task). Speaks the protobuf
// client plane defined in ray_tpu/protocol/raytpu.proto over the head's
// dedicated client port: 4-byte LE length + raytpu.ClientRequest frames.
//
// Cross-language tasks address Python functions by importable name
// ("module.fn"); arguments and results are tagged raytpu.Value payloads,
// so scalars/strings/bytes round-trip without any Python on this side.
#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "raytpu.pb.h"

namespace raytpu_client {

class Client {
 public:
  ~Client();

  // Connect + Init handshake. Returns false on any failure (see error()).
  bool Connect(const std::string& host, int port,
               const std::string& client_name = "cpp");

  // Store a tagged value; returns the object id ("" on failure).
  std::string Put(const raytpu::Value& value);
  std::string PutRaw(const std::string& data);
  std::string PutI64(int64_t v);
  std::string PutF64(double v);
  std::string PutUtf8(const std::string& s);

  // Fetch an object's value. found=false if the wait timed out/errored.
  raytpu::Value Get(const std::string& object_id, double timeout_s,
                    bool* found);

  // Submit a Python function by importable name with tagged-value args;
  // returns the result object ids (empty on failure).
  std::vector<std::string> Submit(const std::string& fn_name,
                                  const std::vector<raytpu::Value>& args,
                                  int num_returns = 1);

  // Cross-language actors (parity: ray::Actor, cpp/include/ray/api.h:130):
  // create a Python actor by importable class name, call its methods with
  // tagged args, wait on the returned object ids, kill it.
  std::string CreateActor(const std::string& class_name,
                          const std::vector<raytpu::Value>& args,
                          double num_cpus = 1.0,
                          const std::string& name = "",
                          const std::string& placement_group_id = "",
                          int bundle_index = -1);

  // Placement groups (parity: ray::PlacementGroup from the C++ API):
  // reserve bundles atomically; actors created with placement_group_id
  // land inside the reservation. ready_timeout_s > 0 blocks until the
  // reservation commits (ready=false on timeout).
  std::string CreatePlacementGroup(
      const std::vector<std::map<std::string, double>>& bundles,
      const std::string& strategy = "PACK",
      const std::string& name = "", double ready_timeout_s = 30.0,
      bool* ready = nullptr);
  bool RemovePlacementGroup(const std::string& placement_group_id);
  std::string CallActor(const std::string& actor_id,
                        const std::string& method,
                        const std::vector<raytpu::Value>& args);
  bool KillActor(const std::string& actor_id, bool no_restart = true);

  // Block until num_returns of object_ids are ready; fills ready ids.
  bool Wait(const std::vector<std::string>& object_ids, int num_returns,
            double timeout_s, std::vector<std::string>* ready);

  // KV convenience (the head's internal KV).
  bool KvPut(const std::string& key, const std::string& value);
  bool KvGet(const std::string& key, std::string* value);

  const std::map<std::string, double>& cluster_resources() const {
    return resources_;
  }
  const std::string& error() const { return error_; }

  // Tagged-value helpers.
  static raytpu::Value I64(int64_t v);
  static raytpu::Value F64(double v);
  static raytpu::Value Utf8(const std::string& s);
  static raytpu::Value Raw(const std::string& data);

 private:
  bool Rpc(raytpu::ClientRequest* req, raytpu::ClientReply* reply);

  int fd_ = -1;
  uint64_t next_req_id_ = 1;
  std::map<std::string, double> resources_;
  std::string error_;
};

}  // namespace raytpu_client
