"""Shared plumbing for the repo's static-analysis gates.

Both analysis planes — `tools.staticcheck` (source conventions) and
`tools.graphcheck` (lowered XLA graphs) — share one findings/debt model:

  Finding       a violation with a line-number-free fingerprint
  suppressed()  inline `# <tool>: ok <rule>` markers (on the line or in
                the comment block above it)
  baseline      a checked-in JSON multiset of accepted findings; new
                findings fail, paid-off debt surfaces as stale

The baseline file is a JSON list of {rule, path, detail} entries —
line-number-free fingerprints, so routine edits above a recorded site do
not churn it. Matching is multiset-aware: two identical recorded entries
absorb two identical findings; a third is NEW and fails the run.

`--update-baseline` rewrites the file from the current findings (the
reviewed way to accept debt); stale entries (recorded but no longer
firing) are reported as warnings and dropped on the next update, so the
debt ledger only ever shrinks by paying it.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import re


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation. `detail` is the line-number-free fingerprint the
    baseline matches on (line numbers drift with every edit; the shape of
    the violation does not)."""

    rule: str        # e.g. "blocking-under-lock"
    path: str        # repo-relative
    line: int        # 1-based; 0 = whole-file finding
    detail: str      # stable fingerprint, no line numbers
    message: str = ""  # human text; defaults to detail

    def render(self) -> str:
        msg = self.message or self.detail
        return f"{self.path}:{self.line}: [{self.rule}] {msg}"

    def key(self) -> tuple:
        return (self.rule, self.path, self.detail)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def suppressed(lines: list, lineno: int, rule: str,
               tool: str = "staticcheck") -> bool:
    """`# <tool>: ok <rule>` on the line, or anywhere in the block of
    comment/blank lines immediately above it (so a marker can open a
    multi-line justification comment)."""
    pat = re.compile(rf"#\s*{tool}:\s*ok\s+([\w,-]+)")

    def marked(ln: int) -> bool:
        m = pat.search(lines[ln - 1])
        return bool(m) and rule in m.group(1).split(",")

    if not 1 <= lineno <= len(lines):
        return False
    if marked(lineno):
        return True
    ln = lineno - 1
    while ln >= 1:
        stripped = lines[ln - 1].strip()
        if stripped and not stripped.startswith("#"):
            return False
        if stripped and marked(ln):
            return True
        ln -= 1
    return False


# ---------------- baseline workflow ----------------


def load_baseline(path: str) -> collections.Counter:
    if not os.path.exists(path):
        return collections.Counter()
    with open(path) as f:
        entries = json.load(f)
    return collections.Counter(
        (e["rule"], e["path"], e["detail"]) for e in entries)


def save_baseline(path: str, findings: list) -> None:
    entries = sorted(
        ({"rule": f.rule, "path": f.path, "detail": f.detail}
         for f in findings),
        key=lambda e: (e["rule"], e["path"], e["detail"]))
    with open(path, "w") as f:
        json.dump(entries, f, indent=1)
        f.write("\n")


def diff_baseline(findings: list, baseline: collections.Counter):
    """-> (new findings, stale baseline keys)."""
    remaining = collections.Counter(baseline)
    new: list[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        if remaining[f.key()] > 0:
            remaining[f.key()] -= 1
        else:
            new.append(f)
    stale = sorted(k for k, n in remaining.items() if n > 0)
    return new, stale


def report(findings: list, bpath: str, *, update: bool = False,
           use_baseline: bool = True, out=None) -> int:
    """The shared CLI tail: diff against the baseline (or rewrite it) and
    print the summary. Returns the exit code (0 clean, 1 new findings)."""
    import sys
    out = out or sys.stdout
    if update:
        save_baseline(bpath, findings)
        print(f"baseline updated: {len(findings)} entries -> {bpath}",
              file=out)
        return 0
    base = (load_baseline(bpath) if use_baseline
            else collections.Counter())
    new, stale = diff_baseline(findings, base)
    for f in new:
        print(f.render(), file=out)
    for key in stale:
        print(f"stale baseline entry (no longer fires): {key}",
              file=sys.stderr)
    n_base = len(findings) - len(new)
    print(f"{len(findings)} finding(s): {len(new)} new, "
          f"{n_base} baselined, {len(stale)} stale baseline entr(ies)",
          file=sys.stderr)
    return 1 if new else 0
