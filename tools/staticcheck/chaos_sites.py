"""Pass 5 — chaos-site registry drift + recovery-path exception hygiene.

The chaos plane (ray_tpu/core/chaos.py) is convention-coupled in two
directions: every `chaos.site("name")` / `chaos.kill(...)` /
`chaos.delay(...)` literal in the source must name a registered site (a
typo'd site silently never fires — the storm "passes" by testing
nothing), and every REGISTERED_SITES entry must still have a seam in the
source (a site whose seam was refactored away keeps appearing in
schedules and docs while injecting nothing). This pass checks both
directions, the same shape as wire_drift's both-ways pinned tables.

Second family: recovery paths. The functions that HANDLE injected faults
(fallbacks, reconnects, reclaim sweeps — the RECOVERY_SCOPES table) must
not swallow errors blind: a bare `except:` or a broad
`except (Base)Exception:` whose body is only pass/continue turns a
recovery bug into silence exactly where the chaos suite is trying to
look. Narrow catches (`except OSError: pass` on an already-dead channel)
are fine; broad-and-silent is the anti-pattern. `# staticcheck: ok
<rule>` suppresses intentional sites, as everywhere else.

  chaos-site-unregistered  source literal not in REGISTERED_SITES
  chaos-site-unused        REGISTERED_SITES entry with no source seam
  chaos-site-dynamic       non-literal site name (unauditable)
  recovery-swallow         bare/broad silent except inside a recovery fn
"""

from __future__ import annotations

import ast
import glob
import os

from tools.staticcheck import Finding
from tools.staticcheck.concurrency import suppressed

TARGET_GLOBS = ("ray_tpu/core/*.py", "ray_tpu/experimental/channel.py",
                "ray_tpu/train/*.py", "ray_tpu/llm/*.py",
                "ray_tpu/serve/*.py",
                # Multi-tenant plane: the job.hostile storm seam lives in
                # core/jobs.py; scale/stop paths get recovery hygiene.
                "ray_tpu/autoscaler/*.py", "ray_tpu/job_submission.py")

_CHAOS_FNS = {"site", "kill", "delay"}

# (repo-relative path, function name) pairs whose bodies are recovery
# paths — the code that must turn an injected fault into a clean outcome.
# Scanned for the recovery-swallow rule; a scope that no longer exists is
# itself a finding (the recovery path was refactored away unreviewed).
RECOVERY_SCOPES: tuple = (
    ("ray_tpu/core/worker.py", "_direct_fallback"),
    ("ray_tpu/core/worker.py", "_on_wpeer_eof"),
    ("ray_tpu/core/node_agent.py", "_direct_fallback"),
    ("ray_tpu/core/node_agent.py", "_on_peer_eof"),
    ("ray_tpu/core/node_agent.py", "_reconnect_or_die"),
    ("ray_tpu/core/node_agent.py", "_spill_to_peer"),
    ("ray_tpu/core/node_agent.py", "_on_lease_spill"),
    ("ray_tpu/core/objxfer.py", "_pull_striped"),
    ("ray_tpu/core/objxfer.py", "_pull_range_fresh"),
    ("ray_tpu/core/objxfer.py", "fetch_from_peer"),
    ("ray_tpu/core/runtime.py", "_redrive_lost_leases"),
    ("ray_tpu/core/runtime.py", "_on_actor_worker_death"),
    ("ray_tpu/core/object_store.py", "release_reservation"),
    ("ray_tpu/core/object_store.py", "reclaim_orphans"),
    # Head-shard plane: the heal pass (shard SIGKILL -> re-slice ->
    # respawn-with-replay -> hand-back) and the dir mirror's dead-shard
    # requeue path; plus the worker-side replayed-task re-seal (a
    # restarted head re-grants tasks whose node_done it never saw).
    ("ray_tpu/core/head_shards.py", "check_and_heal"),
    ("ray_tpu/core/head_shards.py", "_dir_flush_loop"),
    ("ray_tpu/core/worker.py", "_put_with_spill"),
    # Elastic train plane: the code that turns a killed/hung worker or a
    # torn checkpoint into a committed-manifest resume must stay loud.
    ("ray_tpu/train/trainer.py", "_poll_until_done"),
    ("ray_tpu/train/trainer.py", "_commit_if_ready"),
    ("ray_tpu/train/trainer.py", "_resume_path"),
    ("ray_tpu/train/checkpoint.py", "gc_uncommitted"),
    ("ray_tpu/train/checkpoint.py", "load_shard"),
    # Disaggregated LLM serving plane: the code that turns a dropped
    # dispatch, a lost KV handoff, or a decode replica SIGKILLed
    # mid-stream into a completed (exactly-once) request must stay loud.
    ("ray_tpu/llm/serve.py", "_fetch_handoff"),
    ("ray_tpu/llm/serve.py", "_dispatch_decode"),
    ("ray_tpu/llm/serve.py", "_prefill_with_retry"),
    ("ray_tpu/llm/serve.py", "_stream_tokens"),
)
_RECOVERY_FN_NAMES = {name for _p, name in RECOVERY_SCOPES}


def _registered_sites() -> dict:
    from ray_tpu.core.chaos import REGISTERED_SITES
    return REGISTERED_SITES


def _iter_files(root: str, targets: tuple | None):
    if targets:
        for rel in targets:
            yield rel, (rel if os.path.isabs(rel)
                        else os.path.join(root, rel))
        return
    for pat in TARGET_GLOBS:
        for p in sorted(glob.glob(os.path.join(root, pat))):
            yield os.path.relpath(p, root), p


def _is_chaos_call(node: ast.Call) -> str | None:
    """'site'/'kill'/'delay' when the call is chaos.<fn>(...), else None."""
    f = node.func
    if (isinstance(f, ast.Attribute) and f.attr in _CHAOS_FNS
            and isinstance(f.value, ast.Name)
            and f.value.id in ("chaos", "_chaos_mod")):
        return f.attr
    return None


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    """Body is nothing but pass/continue (no re-raise, no logging, no
    fallback action)."""
    for stmt in handler.body:
        if not isinstance(stmt, (ast.Pass, ast.Continue)):
            return False
    return True


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True  # bare except
    names = []
    t = handler.type
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        if isinstance(e, ast.Name):
            names.append(e.id)
        elif isinstance(e, ast.Attribute):
            names.append(e.attr)
    return any(n in ("Exception", "BaseException") for n in names)


def run(root: str, targets: tuple | None = None) -> list:
    findings: list[Finding] = []
    sites = _registered_sites()
    used: dict[str, tuple] = {}  # site -> (rel, line) first use
    scopes_seen: set = set()

    for rel, path in _iter_files(root, targets):
        rel_key = rel if not os.path.isabs(rel) else os.path.basename(rel)
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        lines = src.splitlines()
        tree = ast.parse(src, filename=path)

        def emit(rule, line, detail):
            if not suppressed(lines, line, rule):
                findings.append(Finding(rule, rel_key, line, detail))

        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                kind = _is_chaos_call(node)
                if kind is None or not node.args:
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    emit("chaos-site-dynamic", node.lineno,
                         f"chaos.{kind}(...) with a non-literal site name "
                         "— the registry cross-check cannot audit it")
                    continue
                name = arg.value
                used.setdefault(name, (rel_key, node.lineno))
                if name not in sites:
                    emit("chaos-site-unregistered", node.lineno,
                         f"chaos.{kind}({name!r}) is not in "
                         "chaos.REGISTERED_SITES — it can never be armed")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                in_repo_scope = (rel_key, node.name) in {
                    (p, n) for p, n in RECOVERY_SCOPES}
                in_fixture_scope = (targets is not None
                                    and node.name in _RECOVERY_FN_NAMES)
                if not (in_repo_scope or in_fixture_scope):
                    continue
                scopes_seen.add((rel_key, node.name))
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.ExceptHandler):
                        continue
                    if _handler_is_broad(sub) and _handler_is_silent(sub):
                        emit("recovery-swallow", sub.lineno,
                             f"broad silent except in recovery path "
                             f"{node.name}: an injected fault's recovery "
                             "bug disappears here")

    if targets is None:
        for name in sites:
            if name not in used:
                findings.append(Finding(
                    "chaos-site-unused", "ray_tpu/core/chaos.py", 0,
                    f"registered chaos site {name!r} has no "
                    "chaos.site/kill/delay seam in the source"))
        for pair in RECOVERY_SCOPES:
            if pair not in scopes_seen:
                findings.append(Finding(
                    "recovery-swallow", pair[0], 0,
                    f"pinned recovery scope {pair[1]!r} no longer exists "
                    "in {0}; update RECOVERY_SCOPES after reviewing the "
                    "refactor".format(pair[0])))
    return findings
