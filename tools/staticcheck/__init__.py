"""raytpu-check: repo-native static analysis for the hand-maintained planes.

The reference keeps one generated artifact as the single source of truth
for its wire layer; this protoc-less rebuild instead carries THREE
hand-maintained copies of the schema (raytpu.proto, the hand-authored
descriptors in core/worker_wire.py, the hand-rolled varint codec in
cpp/pb/raytpu.pb.h) plus convention-enforced invariants (~70 lock sites,
two no-pickle planes, closer/join ownership for fds and threads). Each
pass turns one class of convention into a test failure:

  wire_drift    the three schema copies can never silently diverge
  concurrency   blocking calls inside lock-held regions; cross-module
                lock-acquisition-order graph with inversion cycles
  hot_plane     the PR 3/PR 5 invariant: tensor-channel and proto-frame
                payload paths never touch pickle
  resources     sockets/fds/threads created without a registered
                closer/join owner

Run as `python -m tools.staticcheck` (CI: exit nonzero on any finding not
recorded in the checked-in baseline) or through the tier-1 pytest test
(tests/test_staticcheck.py). Intentional sites are suppressed inline with
`# staticcheck: ok <rule>` on the offending line or the line above;
pre-existing debt lives in tools/staticcheck/baseline.json
(`--update-baseline` rewrites it).
"""

from __future__ import annotations

# Findings/suppression/baseline plumbing is shared with tools.graphcheck
# (the lowered-XLA-graph plane); re-exported here so every existing
# `from tools.staticcheck import Finding` caller keeps working.
from tools.checklib import Finding, repo_root  # noqa: F401


PASSES = ("wire_drift", "concurrency", "hot_plane", "resources",
          "chaos_sites")


def run_passes(root: str | None = None,
               passes: tuple = PASSES) -> list[Finding]:
    """Run the requested passes over the repo; returns raw findings
    (baseline not applied — see baseline.diff_against_baseline)."""
    from tools.staticcheck import (chaos_sites, concurrency, hot_plane,
                                   resources, wire_drift)
    root = root or repo_root()
    mods = {"wire_drift": wire_drift, "concurrency": concurrency,
            "hot_plane": hot_plane, "resources": resources,
            "chaos_sites": chaos_sites}
    findings: list[Finding] = []
    for name in passes:
        findings.extend(mods[name].run(root))
    return findings
