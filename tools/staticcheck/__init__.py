"""raytpu-check: repo-native static analysis for the hand-maintained planes.

The reference keeps one generated artifact as the single source of truth
for its wire layer; this protoc-less rebuild instead carries THREE
hand-maintained copies of the schema (raytpu.proto, the hand-authored
descriptors in core/worker_wire.py, the hand-rolled varint codec in
cpp/pb/raytpu.pb.h) plus convention-enforced invariants (~70 lock sites,
two no-pickle planes, closer/join ownership for fds and threads). Each
pass turns one class of convention into a test failure:

  wire_drift    the three schema copies can never silently diverge
  concurrency   blocking calls inside lock-held regions; cross-module
                lock-acquisition-order graph with inversion cycles
  hot_plane     the PR 3/PR 5 invariant: tensor-channel and proto-frame
                payload paths never touch pickle
  resources     sockets/fds/threads created without a registered
                closer/join owner

Run as `python -m tools.staticcheck` (CI: exit nonzero on any finding not
recorded in the checked-in baseline) or through the tier-1 pytest test
(tests/test_staticcheck.py). Intentional sites are suppressed inline with
`# staticcheck: ok <rule>` on the offending line or the line above;
pre-existing debt lives in tools/staticcheck/baseline.json
(`--update-baseline` rewrites it).
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation. `detail` is the line-number-free fingerprint the
    baseline matches on (line numbers drift with every edit; the shape of
    the violation does not)."""

    rule: str        # e.g. "blocking-under-lock"
    path: str        # repo-relative
    line: int        # 1-based; 0 = whole-file finding
    detail: str      # stable fingerprint, no line numbers
    message: str = ""  # human text; defaults to detail

    def render(self) -> str:
        msg = self.message or self.detail
        return f"{self.path}:{self.line}: [{self.rule}] {msg}"

    def key(self) -> tuple:
        return (self.rule, self.path, self.detail)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


PASSES = ("wire_drift", "concurrency", "hot_plane", "resources",
          "chaos_sites")


def run_passes(root: str | None = None,
               passes: tuple = PASSES) -> list[Finding]:
    """Run the requested passes over the repo; returns raw findings
    (baseline not applied — see baseline.diff_against_baseline)."""
    from tools.staticcheck import (chaos_sites, concurrency, hot_plane,
                                   resources, wire_drift)
    root = root or repo_root()
    mods = {"wire_drift": wire_drift, "concurrency": concurrency,
            "hot_plane": hot_plane, "resources": resources,
            "chaos_sites": chaos_sites}
    findings: list[Finding] = []
    for name in passes:
        findings.extend(mods[name].run(root))
    return findings
