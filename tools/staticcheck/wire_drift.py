"""Pass 1 — wire-schema drift across the three hand-maintained copies.

`ray_tpu/protocol/raytpu.proto` is the contract; the implementations are
(a) the Python bindings in the default descriptor pool (the checked-in
raytpu_pb2 plus the hand-authored FileDescriptorProtos core/worker_wire.py
adds at import), (b) the worker_wire.py `_msg(...)` source itself (checked
by AST so a typo is caught even when the import-time pool add would mask
it), and (c) the hand-rolled varint codec cpp/pb/raytpu.pb.h (tag
constants + wire types recovered from the Put*/Parse sites).

Two pinned fallback tables encode the protoc-less reality this repo
documents in the schema comments:

  PICKLE_FRAMED_MESSAGES — messages documented in the proto but absent
    from the checked-in bindings (they ride the pickle framing until the
    next regen). The pin is verified BOTH ways: the proto must still
    declare them at the pinned numbers, and the pool must still lack them
    (a regen that binds one is drift in the pin itself — delete the entry).
  FALLBACK_FIELDS — fields of BOUND messages that are documented but not
    generated (proto_wire.py falls back to pickle framing when they are
    set). Same both-ways verification.
"""

from __future__ import annotations

import ast
import os
import re

from tools.staticcheck import Finding
from tools.staticcheck import protoparse

PROTO_REL = "ray_tpu/protocol/raytpu.proto"
WW_REL = "ray_tpu/core/worker_wire.py"
CPP_REL = "cpp/pb/raytpu.pb.h"

# Messages the checked-in bindings do not carry (pickle framing until the
# next protoc regen): message -> {field name: number}.
PICKLE_FRAMED_MESSAGES = {
    "ClusterViewEntry": {"node_id": 1, "entry_version": 2, "state": 3,
                         "idle_workers": 4, "lease_backlog": 5,
                         "lease_inflight": 6, "cpu": 7, "ctrl_host": 8,
                         "ctrl_port": 9},
    "ClusterView": {"version": 1, "entries": 2},
    "LeaseSpilled": {"moves": 1},
    "LeaseSpilled.Move": {"task_id": 1, "lease_seq": 2, "spill_hops": 3,
                          "to_node_id": 4},
    "TaskEvent": {"task_id": 1, "attempt": 2, "state": 3, "ts": 4,
                  "name": 5, "data": 6},
    "TaskEvents": {"events": 1, "dropped": 2},
    "MetricsUpdate": {"metrics": 1},
    "MetricsUpdate.Metric": {"name": 1, "kind": 2, "description": 3,
                             "tag_keys": 4, "values": 5},
    # Direct worker<->worker actor-call frames (UDS peer plane): pickle
    # framing today, schema documented for the next regen.
    "DirectActorCall": {"spec": 1},
    "DirectActorReply": {"dones": 1},
    "DirectActorReply.Done": {"task_id": 1, "outs": 2},
    # Head-shard plane (core/head_shards.py): pickle framing, map and
    # snapshot payloads are Python structures until regen.
    "ShardHello": {"shard_id": 1},
    "ShardReady": {"shard_id": 1, "n_dir": 2, "n_tev": 3},
    "ShardAssign": {"epoch": 1, "buckets": 2},
    "ShardDirAdd": {"entries": 1},
    "ShardDirAdd.Entry": {"object_id": 1, "node_id": 2},
    "ShardDirDrop": {"object_ids": 1},
    "ShardTevIngest": {"node_id": 1, "events": 2, "dropped": 3},
    "ShardTevDrain": {"req_id": 1},
    "ShardTevBatch": {"req_id": 1, "batches": 2},
    "ShardSnapshot": {"req_id": 1},
    "ShardState": {"req_id": 1, "epoch": 2, "directory": 3,
                   "tev_pending": 4},
    "ShardShutdown": {},
}

# Fields of bound messages that ride the pickle-framing fallback when set
# (documented in the proto, absent from the generated classes).
FALLBACK_FIELDS = {
    "TaskSpec": {"language": 21, "job_id": 22},
    "RegisterNode.WorkerInventory": {"language": 4},
    "AgentFrame": {"cluster_view": 11, "lease_spilled": 12,
                   "task_events": 13, "metrics_update": 14},
}

# cpp class -> proto message(s) it implements (identity unless listed).
CPP_ALIASES = {
    "SimpleOkReply": ("RemovePlacementGroupReply", "KillActorReply",
                      "KvPutReply"),
}
# Worker-plane messages the cpp codec must materialize COMPLETELY (the
# client-plane classes are deliberate subsets; unknown fields skip).
CPP_COMPLETE = ("WorkerHello", "WorkerOut", "WorkerDone")
# Messages the C++ frontends depend on: a missing class is drift.
CPP_REQUIRED = (
    "Value", "Arg", "TaskArgs", "TaskSpec", "WorkerHello", "WorkerOut",
    "WorkerDone", "WorkerFrame", "InitRequest", "InitReply", "PutRequest",
    "PutReply", "GetRequest", "GetReply", "SubmitRequest", "SubmitReply",
    "WaitRequest", "WaitReply", "CreateActorRequest", "CreateActorReply",
    "Bundle", "CreatePlacementGroupRequest", "CreatePlacementGroupReply",
    "RemovePlacementGroupRequest", "ActorCallRequest", "ActorCallReply",
    "KillActorRequest", "KvPutRequest", "KvGetRequest", "KvGetReply",
    "SimpleOkReply", "ClientRequest", "ClientReply",
)

RULE = "wire-drift"

# The native scheduling cores' shared AgentFrame oneof sniffer table
# (cpp/frame_core.h kAgentFrameTags, compiled into BOTH agent_core.cc
# and head_core.cc): cross-checked BOTH WAYS below, and each core is
# verified to actually include the shared header (a fork of the table
# would silently escape the pin).
FRAME_CORE_REL = "cpp/frame_core.h"
NATIVE_CORES = ("cpp/agent_core.cc", "cpp/head_core.cc")


def run(root: str, proto_path: str | None = None,
        ww_path: str | None = None, cpp_path: str | None = None,
        frame_core_path: str | None = None, use_pool: bool = True,
        native_core_paths: tuple | None = None) -> list:
    """All five cross-checks. Path overrides exist for the mutation
    tests (run the real implementations against a doctored schema)."""
    proto_path = proto_path or os.path.join(root, PROTO_REL)
    ww_path = ww_path or os.path.join(root, WW_REL)
    cpp_path = cpp_path or os.path.join(root, CPP_REL)
    frame_core_path = frame_core_path or os.path.join(root, FRAME_CORE_REL)
    findings: list[Finding] = []
    try:
        schema = protoparse.parse(proto_path)
    except ValueError as e:
        return [Finding(RULE, PROTO_REL, 0, f"unparseable schema: {e}")]
    if use_pool:
        findings += check_pool(schema)
    findings += check_worker_wire(schema, ww_path)
    findings += check_cpp_header(schema, cpp_path)
    findings += check_frame_tags(schema, frame_core_path)
    findings += check_native_cores_share_table(root, native_core_paths)
    return findings


# ---------------- (a) descriptor-pool bindings ----------------

def _pool_wire_type(fd) -> int | None:
    from google.protobuf.descriptor import FieldDescriptor as F
    wt = {F.TYPE_INT32: 0, F.TYPE_INT64: 0, F.TYPE_UINT32: 0,
          F.TYPE_UINT64: 0, F.TYPE_SINT32: 0, F.TYPE_SINT64: 0,
          F.TYPE_BOOL: 0, F.TYPE_ENUM: 0, F.TYPE_FIXED64: 1,
          F.TYPE_SFIXED64: 1, F.TYPE_DOUBLE: 1, F.TYPE_FIXED32: 5,
          F.TYPE_SFIXED32: 5, F.TYPE_FLOAT: 5, F.TYPE_STRING: 2,
          F.TYPE_BYTES: 2, F.TYPE_MESSAGE: 2}
    return wt.get(fd.type)


def check_pool(schema: dict) -> list:
    """Every proto message vs the live descriptor pool (raytpu_pb2 +
    worker_wire's import-time additions)."""
    import ray_tpu.core.worker_wire  # noqa: F401 — adds Worker* to pool
    import ray_tpu.protocol.raytpu_pb2  # noqa: F401
    from google.protobuf import descriptor_pool
    pool = descriptor_pool.Default()
    out: list[Finding] = []

    for name, msg in schema.items():
        if name.endswith("#entry"):
            continue  # synthesized map entries: covered via the map field
        pinned = PICKLE_FRAMED_MESSAGES.get(name)
        try:
            desc = pool.FindMessageTypeByName(f"raytpu.{name}")
        except KeyError:
            desc = None
        if pinned is not None:
            if desc is not None:
                out.append(Finding(
                    RULE, PROTO_REL, 0,
                    f"{name}: pinned as pickle-framed but the pool now "
                    "binds it — regen landed; delete its "
                    "PICKLE_FRAMED_MESSAGES entry"))
                continue
            # Verify the pin still matches the schema (a schema edit that
            # renumbers a pickle-framed message is exactly the silent
            # drift the pickle path cannot catch at runtime).
            declared = {f.name: f.number for f in msg.fields.values()}
            if declared != pinned:
                out.append(Finding(
                    RULE, PROTO_REL, 0,
                    f"{name}: proto declares {declared} but the "
                    f"pickle-framing pin expects {pinned}"))
            continue
        if desc is None:
            out.append(Finding(
                RULE, PROTO_REL, 0,
                f"{name}: declared in raytpu.proto but absent from the "
                "python bindings (and not pinned as pickle-framed)"))
            continue
        fallback = FALLBACK_FIELDS.get(name, {})
        bound = {f.name: f for f in desc.fields}
        for f in msg.fields.values():
            if f.name in fallback:
                if fallback[f.name] != f.number:
                    out.append(Finding(
                        RULE, PROTO_REL, 0,
                        f"{name}.{f.name}: proto number {f.number} != "
                        f"pickle-fallback pin {fallback[f.name]}"))
                if f.name in bound:
                    out.append(Finding(
                        RULE, PROTO_REL, 0,
                        f"{name}.{f.name}: pinned as a pickle-fallback "
                        "field but the bindings now carry it — delete "
                        "its FALLBACK_FIELDS entry"))
                continue
            bf = bound.get(f.name)
            if bf is None:
                out.append(Finding(
                    RULE, PROTO_REL, 0,
                    f"{name}.{f.name}: in raytpu.proto but not in the "
                    "python bindings"))
                continue
            if bf.number != f.number:
                out.append(Finding(
                    RULE, PROTO_REL, 0,
                    f"{name}.{f.name}: field number {f.number} in proto "
                    f"vs {bf.number} in the python bindings"))
            pwt = _pool_wire_type(bf)
            if pwt is not None and pwt != f.wire_type:
                out.append(Finding(
                    RULE, PROTO_REL, 0,
                    f"{name}.{f.name}: wire type {f.wire_type} in proto "
                    f"vs {pwt} in the python bindings"))
        for bname, bf in bound.items():
            if bname not in msg.fields:
                out.append(Finding(
                    RULE, PROTO_REL, 0,
                    f"{name}.{bname}: in the python bindings (number "
                    f"{bf.number}) but not in raytpu.proto"))
    return out


# ---------------- (b) worker_wire.py hand-authored descriptors ----------------

_TYPE_ATTR_TO_PROTO = {
    "TYPE_BYTES": "bytes", "TYPE_STRING": "string", "TYPE_INT32": "int32",
    "TYPE_INT64": "int64", "TYPE_UINT64": "uint64", "TYPE_BOOL": "bool",
    "TYPE_DOUBLE": "double", "TYPE_FLOAT": "float",
}


def check_worker_wire(schema: dict, path: str) -> list:
    """AST cross-check of every `_msg(f, "Name", [...])` field tuple in
    worker_wire.py against the schema — source-level, so a bad edit is
    caught even if a stale pool already holds the old (correct) shape."""
    rel = WW_REL
    out: list[Finding] = []
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    seen: dict[str, dict] = {}  # msg name -> {fname: (num, type, rep, line)}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "_msg" and len(node.args) == 3):
            continue
        mname = node.args[1].value
        fields = {}
        for t in node.args[2].elts:
            fname, num, ftype, tname, rep = t.elts
            if isinstance(ftype, ast.Attribute):
                type_attr = ftype.attr
            else:
                type_attr = "?"
            tn = tname.value if isinstance(tname, ast.Constant) else None
            if type_attr == "TYPE_MESSAGE":
                ptype = (tn or "").removeprefix(".raytpu.")
            else:
                ptype = _TYPE_ATTR_TO_PROTO.get(type_attr, "?")
            fields[fname.value] = (num.value, ptype, bool(rep.value),
                                   t.lineno)
        seen[mname] = fields

    for mname, fields in seen.items():
        msg = schema.get(mname)
        if msg is None:
            out.append(Finding(
                RULE, rel, 0,
                f"{mname}: descriptor built in worker_wire.py but the "
                "message is not in raytpu.proto"))
            continue
        for fname, (num, ptype, rep, line) in fields.items():
            pf = msg.fields.get(fname)
            if pf is None:
                out.append(Finding(
                    RULE, rel, line,
                    f"{mname}.{fname}: in worker_wire.py but not in "
                    "raytpu.proto"))
                continue
            if pf.number != num:
                out.append(Finding(
                    RULE, rel, line,
                    f"{mname}.{fname}: field number {num} in "
                    f"worker_wire.py vs {pf.number} in raytpu.proto"))
            if pf.type != ptype:
                out.append(Finding(
                    RULE, rel, line,
                    f"{mname}.{fname}: type {ptype} in worker_wire.py "
                    f"vs {pf.type} in raytpu.proto"))
            if pf.repeated != rep:
                out.append(Finding(
                    RULE, rel, line,
                    f"{mname}.{fname}: repeated={rep} in worker_wire.py "
                    f"vs {pf.repeated} in raytpu.proto"))
        for fname, pf in msg.fields.items():
            if fname not in fields:
                out.append(Finding(
                    RULE, rel, 0,
                    f"{mname}.{fname}: in raytpu.proto (number "
                    f"{pf.number}) but missing from the worker_wire.py "
                    "descriptor"))
    # The worker plane must be fully mirrored here (these bindings are
    # how Python speaks to the C++ worker at all).
    for mname in ("WorkerHello", "WorkerExec", "WorkerOut", "WorkerDone",
                  "WorkerShutdown", "WorkerFrame"):
        if mname in schema and mname not in seen:
            out.append(Finding(
                RULE, rel, 0,
                f"{mname}: worker-plane message has no worker_wire.py "
                "descriptor"))
    return out


# ---------------- (c) cpp/pb/raytpu.pb.h tag constants ----------------

_PUT = re.compile(
    r"pbwire::Put(LenField|LenAlways|Int|Bool|Double|MapSD)"
    r"\(\s*[^,()]*,\s*(\d+)\s*,")
_FWT = re.compile(r"f == (\d+) && wt == (\d+)")
_CASE = re.compile(r"case (\d+):")
_WHICH = re.compile(r"which_ = (\d+)")
_PUT_WT = {"LenField": 2, "LenAlways": 2, "MapSD": 2, "Int": 0,
           "Bool": 0, "Double": 1}


def _cpp_classes(text: str) -> dict:
    """{class name: (body text, start line)} for namespace raytpu."""
    ns = text.find("namespace raytpu")
    if ns < 0:
        return {}
    out = {}
    for m in re.finditer(r"^(?:class|struct) (\w+)", text[ns:], re.M):
        name = m.group(1)
        start = ns + m.start()
        brace = text.find("{", start)
        if brace < 0:
            continue
        depth, i = 1, brace + 1
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        out[name] = (text[brace:i], text[:start].count("\n") + 1)
    return out


def _case_wire_type(body: str, pos: int) -> int | None:
    """Wire type implied by a `case N:` arm: what reader call consumes it
    (up to the next break/case)."""
    stop = len(body)
    for marker in ("break", "case ", "default:"):
        j = body.find(marker, pos)
        if 0 <= j < stop:
            stop = j
    seg = body[pos:stop]
    if "r.Bytes()" in seg or "r.View(" in seg or ".Parse(" in seg:
        return 2
    if "r.Varint()" in seg:
        return 0
    if "r.Double()" in seg:
        return 1
    return None


def _class_evidence(body: str, base_line: int) -> list:
    """[(field number, wire type | None, line)] tag uses in one class."""
    ev = []

    def line_of(pos):
        return base_line + body[:pos].count("\n")

    for m in _PUT.finditer(body):
        ev.append((int(m.group(2)), _PUT_WT[m.group(1)], line_of(m.start())))
    for m in _FWT.finditer(body):
        ev.append((int(m.group(1)), int(m.group(2)), line_of(m.start())))
    for m in _CASE.finditer(body):
        ev.append((int(m.group(1)), _case_wire_type(body, m.end()),
                   line_of(m.start())))
    for m in _WHICH.finditer(body):
        # ClientRequest oneof arm selectors; every arm is a message (wt 2).
        # 0 is the "nothing set" initializer, not a tag.
        if int(m.group(1)) > 0:
            ev.append((int(m.group(1)), 2, line_of(m.start())))
    return ev


# ------------- (d) cpp/frame_core.h AgentFrame sniffer tags -------------
#
# The shared native frame pump labels proto-framed control messages by
# their outermost AgentFrame oneof tag (kAgentFrameTags in frame_core.h,
# compiled into both the agent and head cores). Drift directions: a
# renumber/rename in EITHER place desynchronizes the label from the
# message, and an AgentFrame field the table does not carry leaves the
# native pumps blind to a control message (it would surface unlabeled
# and cost Python a trial decode — or worse, be labeled wrong after a
# renumber). Both directions are findings.

_AGC_TABLE_RE = re.compile(
    r"kAgentFrameTags\[\]\s*=\s*\{(.*?)\};", re.S)
_AGC_ENTRY_RE = re.compile(r'\{\s*(\d+)\s*,\s*"(\w+)"\s*\}')


def check_frame_tags(schema: dict, path: str) -> list:
    rel = FRAME_CORE_REL
    if not os.path.exists(path):
        return [Finding(RULE, rel, 0,
                        "shared native-core header missing (the sniffer "
                        "tag table is pinned here)")]
    with open(path) as f:
        text = f.read()
    m = _AGC_TABLE_RE.search(text)
    if m is None:
        return [Finding(RULE, rel, 0,
                        "kAgentFrameTags table not found (the native "
                        "proto sniffer lost its pin)")]
    base_line = text[:m.start()].count("\n") + 1
    table: dict[int, tuple[str, int]] = {}
    for em in _AGC_ENTRY_RE.finditer(m.group(1)):
        line = base_line + m.group(1)[:em.start()].count("\n")
        table[int(em.group(1))] = (em.group(2), line)
    af = schema.get("AgentFrame")
    if af is None:
        return [Finding(RULE, PROTO_REL, 0,
                        "AgentFrame missing from raytpu.proto but pinned "
                        "by the native sniffer")]
    out: list[Finding] = []
    by_num = af.by_number()
    for num, (name, line) in table.items():
        pf = by_num.get(num)
        if pf is None:
            out.append(Finding(
                RULE, rel, line,
                f"kAgentFrameTags: tag {num} ({name!r}) but AgentFrame "
                f"has no field {num} in raytpu.proto"))
        elif pf.name != name:
            out.append(Finding(
                RULE, rel, line,
                f"kAgentFrameTags: tag {num} named {name!r} but "
                f"raytpu.proto calls AgentFrame.{num} {pf.name!r}"))
    for num, pf in by_num.items():
        if num not in table:
            out.append(Finding(
                RULE, rel, base_line,
                f"AgentFrame.{pf.name} (field {num}) missing from "
                "kAgentFrameTags — the native pump cannot label it"))
    return out


def check_native_cores_share_table(root: str,
                                   core_paths: tuple | None = None) -> list:
    """Both native cores must compile the SHARED tag table: each .cc has
    to include frame_core.h, and neither may re-declare kAgentFrameTags
    locally — a forked copy would drift outside the pin above."""
    out: list[Finding] = []
    rels = NATIVE_CORES if core_paths is None else None
    paths = ([(r, os.path.join(root, r)) for r in rels] if rels is not None
             else [(p, p) for p in core_paths])
    for rel, path in paths:
        if not os.path.exists(path):
            out.append(Finding(
                RULE, rel, 0,
                "native core source missing (the scheduling plane's "
                "native split pins both halves here)"))
            continue
        with open(path) as f:
            text = f.read()
        if '#include "frame_core.h"' not in text:
            out.append(Finding(
                RULE, rel, 1,
                "native core no longer includes frame_core.h — its "
                "sniffer escaped the shared kAgentFrameTags pin"))
        m = _AGC_TABLE_RE.search(text)
        if m is not None:
            out.append(Finding(
                RULE, rel, text[:m.start()].count("\n") + 1,
                "local kAgentFrameTags declaration forks the shared "
                "table in frame_core.h — delete it"))
    return out


def check_cpp_header(schema: dict, path: str) -> list:
    rel = CPP_REL
    out: list[Finding] = []
    with open(path) as f:
        text = f.read()
    classes = _cpp_classes(text)
    for req in CPP_REQUIRED:
        if req not in classes and req not in CPP_ALIASES.values():
            out.append(Finding(
                RULE, rel, 0,
                f"{req}: required by the C++ frontends but no class in "
                "the hand-rolled codec"))
    for cname, (body, base_line) in classes.items():
        targets = CPP_ALIASES.get(cname, (cname,))
        msgs = [schema[t] for t in targets if t in schema]
        if not msgs:
            out.append(Finding(
                RULE, rel, base_line,
                f"{cname}: class in the hand-rolled codec but no such "
                "message in raytpu.proto"))
            continue
        evidence = _class_evidence(body, base_line)
        seen_nums = set()
        for num, wt, line in evidence:
            seen_nums.add(num)
            for msg in msgs:
                pf = msg.by_number().get(num)
                if pf is None:
                    out.append(Finding(
                        RULE, rel, line,
                        f"{cname}: tag {num} used in the codec but "
                        f"{msg.full_name} has no field {num} in "
                        "raytpu.proto"))
                elif wt is not None and wt != pf.wire_type:
                    out.append(Finding(
                        RULE, rel, line,
                        f"{cname}: field {num} ({msg.full_name}."
                        f"{pf.name}) encoded/parsed as wire type {wt} "
                        f"but raytpu.proto says {pf.wire_type}"))
        if cname in CPP_COMPLETE:
            for pf in msgs[0].fields.values():
                if pf.number not in seen_nums:
                    out.append(Finding(
                        RULE, rel, base_line,
                        f"{cname}.{pf.name}: worker-plane field (number "
                        f"{pf.number}) missing from the hand-rolled "
                        "codec"))
    return out
