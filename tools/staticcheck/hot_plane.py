"""Pass 3 — the no-pickle invariant on the hot planes.

Two planes promise "no pickle on payload paths" by convention:

  PR 5, proto-frame plane: core/worker_wire.py and the agent's cpp-worker
  dispatch path never touch pickle at all — every frame a C++ worker
  reads or writes is protobuf, every arena arg/return is tagged.
  (The one sanctioned exception: converting a cpp error into a Python
  TaskError AFTER the frame is decoded — the language boundary.)

  PR 3, tensor-channel plane: TensorChannel stages array leaf BYTES
  straight into shm; only the pytree skeleton rides the sidecar pickle.
  The functions that handle leaf bytes must therefore never reference
  pickle — a pickle call creeping into one silently reopens the copy
  the zero-copy plane exists to close.

Statically enforced as: banned scopes (whole module, or class.func /
func within a module) may not reference pickle/cloudpickle or the
pickle-wrapping serializers. Scope lists are pinned here; moving a
function out of a scope is a reviewed edit, not a silent drift.
"""

from __future__ import annotations

import ast
import os

from tools.staticcheck import Finding
from tools.staticcheck.concurrency import suppressed

RULE = "pickle-on-hot-plane"

# module -> None (whole module banned) or tuple of banned qualnames.
SCOPES = {
    # The proto-frame bindings: nothing in this module may pickle.
    "ray_tpu/core/worker_wire.py": None,
    # The agent's cpp dispatch/ingest path (frames + arena staging).
    # _on_cpp_done is deliberately absent: it converts cpp errors to
    # TaskError payloads at the language boundary, after the frame.
    "ray_tpu/core/node_agent.py": (
        "NodeAgent._pump_cpp_leases",
        "NodeAgent._on_cpp_frames",
        "NodeAgent._stage_cpp_deps",
        "NodeAgent._spawn_cpp_worker",
        "NodeAgent._cpp_worker_binary",
    ),
    # Tensor-leaf byte handling (the skeleton sidecar lives in
    # _FramePlan.__init__ / _decode_frame, which ARE allowed to pickle).
    "ray_tpu/experimental/channel.py": (
        "_extract",
        "_leaf_kind",
        "_leaf_spec",
        "_host_view",
        "TensorChannel._copy_leaf",
        "TensorChannel._native_copy",
    ),
    # The arena's tagged-object encoder (what a C++ worker reads raw),
    # the write-reservation fill plane (lock-free carve/publish —
    # raw byte moves only; serialization happens in the callers), and
    # the arrow block codec (PR 15): the IPC stream writes straight into
    # the acquired buffer and re-hydrates over a zero-copy arena view —
    # a pickle call creeping in reopens the per-block copy the
    # arena-native data plane exists to close.
    "ray_tpu/core/object_store.py": (
        "SharedMemoryStore.put_tagged",
        "SharedMemoryStore._reserved_create",
        "SharedMemoryStore._carve",
        "_ReservedBuffer.seal",
        "SharedMemoryStore.put_arrow",
        "SharedMemoryStore._decode_arrow",
        "_ArrowKeepalive.__del__",
    ),
    # The direct actor-call frame plane (worker<->worker UDS): routing
    # and shipping only — payload (de)serialization belongs to
    # _apply_direct_done/_reply_result, never to the frame movers.
    "ray_tpu/core/worker.py": (
        "WorkerRuntime.send_direct_worker",
        "WorkerRuntime._on_wpeer_frame",
        "_ReplyBatcher._send",
        "_ReplyBatcher._group_routes",
    ),
    # PR 14, native head ingest seams: the natively-parsed completion
    # drain rebuilds _on_node_done entries from C++ records — a pickle
    # call creeping in reopens exactly the per-frame unpickle the head
    # core exists to close. (Cold frames still unpickle in
    # _listen_loop_native, which is deliberately NOT scoped.)
    "ray_tpu/core/runtime.py": (
        "Runtime._drain_native_completions",
        "Runtime._accept_pending",
    ),
    # The head core's ctypes binding moves raw bytes only; payload
    # (de)serialization belongs to the runtime's policy layer.
    "ray_tpu/_native/head_core.py": None,
}

_PICKLE_NAMES = {"pickle", "cloudpickle", "_pickle", "_MsgPickler",
                 "Pickler", "Unpickler", "PickleBuffer"}
_WRAPPER_CALLS = {"serialize_value", "deserialize"}


def _pickle_refs(fn_node) -> list:
    """(lineno, description) for every pickle touch inside a scope."""
    out = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Name) and node.id in _PICKLE_NAMES:
            out.append((node.lineno, f"reference to {node.id}"))
        elif isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) \
                    and node.value.id in _PICKLE_NAMES:
                out.append((node.lineno,
                            f"call of {node.value.id}.{node.attr}"))
        elif isinstance(node, ast.Call):
            f = node.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else None)
            if name in _WRAPPER_CALLS:
                out.append((node.lineno,
                            f"pickle-wrapping serializer {name}()"))
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            mods = [a.name for a in node.names]
            if isinstance(node, ast.ImportFrom) and node.module:
                mods.append(node.module)
            for m in mods:
                if m.split(".")[0] in _PICKLE_NAMES:
                    out.append((node.lineno, f"import of {m}"))
    return out


def _iter_scopes(tree, wanted):
    """Yield (qualname, node) for module functions and class methods."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if wanted is None or node.name in wanted:
                yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{node.name}.{sub.name}"
                    if wanted is None or q in wanted:
                        yield q, sub


def run(root: str, scopes: dict | None = None) -> list:
    findings: list[Finding] = []
    for rel, wanted in (scopes or SCOPES).items():
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            findings.append(Finding(
                RULE, rel, 0, "scoped module missing — update SCOPES"))
            continue
        with open(path) as f:
            src = f.read()
        lines = src.splitlines()
        tree = ast.parse(src, filename=path)
        if wanted is None:
            refs = _pickle_refs(tree)
            for line, desc in refs:
                if not suppressed(lines, line, RULE):
                    findings.append(Finding(
                        RULE, rel, line,
                        f"{desc} in no-pickle module"))
            continue
        found = set()
        for qual, node in _iter_scopes(tree, set(wanted)):
            found.add(qual)
            for line, desc in _pickle_refs(node):
                if not suppressed(lines, line, RULE):
                    findings.append(Finding(
                        RULE, rel, line,
                        f"{desc} on payload path {qual}"))
        for qual in set(wanted) - found:
            findings.append(Finding(
                RULE, rel, 0,
                f"payload-path scope {qual} no longer exists — the "
                "no-pickle surface moved; update SCOPES"))
    return findings
