"""CLI: `python -m tools.staticcheck [--passes a,b] [--update-baseline]`.

Exit codes: 0 clean (all findings covered by the baseline), 1 new
violations, 2 usage/internal error. `--all` additionally runs the
lowered-XLA-graph plane (`tools.graphcheck`) and merges exit codes, so
ONE command gates the whole static plane:

    python -m tools.staticcheck --all
"""

from __future__ import annotations

import argparse
import os
import sys

from tools.staticcheck import PASSES, repo_root, run_passes
from tools.staticcheck import baseline as baseline_mod


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.staticcheck",
        description="raytpu-check: wire-drift + concurrency + hot-plane "
                    "+ resource-hygiene static analysis")
    p.add_argument("--passes", default=",".join(PASSES),
                   help=f"comma list of {', '.join(PASSES)}")
    p.add_argument("--root", default=repo_root())
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default <root>/"
                        f"{baseline_mod.BASELINE_REL})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignore the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept current findings as the new baseline")
    p.add_argument("--files", default=None,
                   help="comma list of python files: restrict the "
                        "concurrency/resources passes to exactly these, "
                        "and treat each as a module-level no-pickle "
                        "scope for hot_plane (fixture/debug mode; "
                        "wire_drift is skipped)")
    p.add_argument("--all", action="store_true",
                   help="also run tools.graphcheck (lowered-XLA-graph "
                        "gates) and tools.racecheck (thread-escape + "
                        "interleaving model checking); exit nonzero if "
                        "ANY plane reports new findings")
    args = p.parse_args(argv)

    passes = tuple(s for s in args.passes.split(",") if s)
    for s in passes:
        if s not in PASSES:
            print(f"unknown pass {s!r} (have: {', '.join(PASSES)})",
                  file=sys.stderr)
            return 2
    if args.files:
        findings = _run_on_files(args.root, passes,
                                 tuple(args.files.split(",")))
    else:
        findings = run_passes(args.root, passes)

    bpath = args.baseline or os.path.join(args.root,
                                          baseline_mod.BASELINE_REL)
    if args.update_baseline:
        baseline_mod.save(bpath, findings)
        print(f"baseline updated: {len(findings)} entries -> {bpath}")
        return 0
    import collections
    base = (baseline_mod.load(bpath) if not args.no_baseline
            else collections.Counter())
    new, stale = baseline_mod.diff(findings, base)
    for f in new:
        print(f.render())
    for key in stale:
        print(f"stale baseline entry (no longer fires): {key}",
              file=sys.stderr)
    n_base = len(findings) - len(new)
    print(f"{len(findings)} finding(s): {len(new)} new, "
          f"{n_base} baselined, {len(stale)} stale baseline entr(ies)",
          file=sys.stderr)
    rc = 1 if new else 0
    if args.all:
        print("--- graphcheck (lowered-XLA-graph plane) ---",
              file=sys.stderr)
        from tools.graphcheck.__main__ import main as graph_main
        grc = graph_main(["--root", args.root]
                         + (["--no-baseline"] if args.no_baseline else []))
        rc = max(rc, grc)
        print("--- racecheck (concurrency-semantics plane) ---",
              file=sys.stderr)
        from tools.racecheck.__main__ import main as race_main
        rrc = race_main(["--root", args.root]
                        + (["--no-baseline"] if args.no_baseline else []))
        rc = max(rc, rrc)
    return rc


def _run_on_files(root: str, passes: tuple, files: tuple) -> list:
    from tools.staticcheck import (chaos_sites, concurrency, hot_plane,
                                   resources)
    rels = tuple(os.path.relpath(os.path.abspath(f), root) for f in files)
    findings = []
    if "concurrency" in passes:
        findings += concurrency.run(root, targets=rels)
    if "resources" in passes:
        findings += resources.run(root, targets=rels)
    if "hot_plane" in passes:
        findings += hot_plane.run(root, scopes={r: None for r in rels})
    if "chaos_sites" in passes:
        findings += chaos_sites.run(root, targets=rels)
    return findings


if __name__ == "__main__":
    sys.exit(main())
