"""Pass 4 — resource hygiene: fds/sockets/threads need a registered owner.

Three rules, tuned for the failure shapes this codebase has actually
shipped (and reverted) — leaked log fds on worker spawn, sockets dropped
on dial failure, reader threads nobody joins:

  fd-inline-arg   an open()/socket()/dial() call used directly as an
                  argument to another call: no name ever binds the fd,
                  so no closer can exist (e.g. Popen(stdout=open(...))).
  fd-no-closer    a socket/fd bound to a local that neither escapes
                  (returned, stored, passed on, captured by a closure)
                  nor is ever close()d/shutdown() in the function.
  fd-use-unguarded a bound socket used for network I/O (connect/send/
                  recv) before ownership transfers, where the use can
                  raise out of the function without any enclosing
                  try closing the fd — the classic dial-failure leak.
  unjoined-thread a non-daemon Thread with no .join() owner in sight:
                  process exit will hang on it, or nobody reaps it.

Ownership transfer is deliberately generous (any call taking the name,
any store) — the pass prefers missing a leak to crying wolf; the
fixtures pin the shapes it must catch.
"""

from __future__ import annotations

import ast
import glob
import os

from tools.staticcheck import Finding
from tools.staticcheck.concurrency import suppressed

TARGET_GLOBS = ("ray_tpu/core/*.py", "ray_tpu/experimental/channel.py",
                "ray_tpu/train/*.py", "ray_tpu/tune/*.py",
                "ray_tpu/llm/serve.py", "ray_tpu/data/*.py",
                # Multi-tenant plane: supervisor log fds + autoscaler
                # provider/node-agent spawns.
                "ray_tpu/autoscaler/*.py", "ray_tpu/job_submission.py")

_FD_CTORS = {
    ("socket", "socket"), ("socket", "create_connection"),
    ("socket", "fromfd"), ("os", "open"), ("os", "fdopen"),
}
_FD_CTOR_NAMES = {"open", "dial", "make_socketpair", "socketpair",
                  "socket_from_fd"}
_RISKY_USES = {"connect", "sendall", "send", "recv", "recv_into",
               "sendmsg", "makefile"}
_CLOSERS = {"close", "shutdown", "detach"}


def _is_fd_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _FD_CTOR_NAMES
    if isinstance(f, ast.Attribute):
        base = f.value.id if isinstance(f.value, ast.Name) else None
        if (base, f.attr) in _FD_CTORS:
            return True
        # socket_mod.socketpair-style aliased imports
        return f.attr in ("socketpair", "create_connection") \
            and base is not None and "socket" in base
    return False


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr == "Thread"
    return isinstance(f, ast.Name) and f.id == "Thread"


def _names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _FnScan:
    """Per-function facts about one tracked fd name."""

    def __init__(self, name: str, line: int):
        self.name = name
        self.line = line
        self.closed = False
        self.escape_line: int | None = None   # earliest positional escape
        self.closure_escape = False           # captured: position unknown
        self.risky: list[tuple] = []          # (lineno, attr, try_stack)


def run(root: str, targets: tuple | None = None) -> list:
    findings: list[Finding] = []
    rels = []
    for pat in (targets or TARGET_GLOBS):
        if os.path.isabs(pat) or os.path.exists(os.path.join(root, pat)):
            rels.append(pat)
        else:
            rels.extend(sorted(
                os.path.relpath(p, root)
                for p in glob.glob(os.path.join(root, pat))))
    for rel in rels:
        path = rel if os.path.isabs(rel) else os.path.join(root, rel)
        with open(path) as f:
            src = f.read()
        lines = src.splitlines()
        tree = ast.parse(src, filename=path)
        _scan_module(tree, rel if not os.path.isabs(rel)
                     else os.path.basename(rel), lines, findings)
    return findings


def _scan_module(tree, rel: str, lines: list, findings: list):
    def emit(rule, line, detail):
        if not suppressed(lines, line, rule):
            findings.append(Finding(rule, rel, line, detail))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_function(node, emit, tree)
        # Inline fd args anywhere (module level included).
        if isinstance(node, ast.Call):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Call) and _is_fd_ctor(arg) \
                        and not _in_with_context(tree, arg):
                    ctor = ast.unparse(arg.func)
                    emit("fd-inline-arg", arg.lineno,
                         f"{ctor}(...) passed inline to "
                         f"{ast.unparse(node.func)}(...): the fd has no "
                         "name and no closer")


def _in_with_context(tree, call) -> bool:
    for w in ast.walk(tree):
        if isinstance(w, (ast.With, ast.AsyncWith)):
            for item in w.items:
                if item.context_expr is call:
                    return True
    return False


def _scan_function(fn, emit, module_tree):
    _scan_fds(fn, emit)
    _scan_threads(fn, emit, module_tree)


# ---------------- fds ----------------


def _walk_shallow(fn):
    """ast.walk minus nested function bodies (those are scanned as their
    own functions)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _scan_fds(fn, emit):
    tracked: dict[str, _FnScan] = {}
    with_names: set = set()
    for node in _walk_shallow(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    with_names |= _names_in(item.optional_vars)
                if isinstance(item.context_expr, ast.Call) \
                        and _is_fd_ctor(item.context_expr):
                    with_names.add("!ctx")  # with open(...) — owned
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)\
                and _is_fd_ctor(node.value):
            names = []
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, ast.Tuple):
                    names.extend(e.id for e in t.elts
                                 if isinstance(e, ast.Name))
            for n in names:
                tracked.setdefault(n, _FnScan(n, node.lineno))
    if not tracked:
        return
    tracked = {n: s for n, s in tracked.items() if n not in with_names}

    # One pass with an explicit try-ancestor stack for guard resolution.
    def visit(node, try_stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            for name in _names_in(node):
                if name in tracked:
                    tracked[name].closure_escape = True
            return
        if isinstance(node, ast.Try):
            for s in node.body:
                visit(s, try_stack + [node])
            for h in node.handlers:
                for s in h.body:
                    visit(s, try_stack)
            for s in node.orelse + node.finalbody:
                visit(s, try_stack)
            return
        _classify(node, try_stack)
        for child in ast.iter_child_nodes(node):
            visit(child, try_stack)

    def _mark_escape(name, line):
        s = tracked.get(name)
        if s is not None and (s.escape_line is None or line < s.escape_line):
            s.escape_line = line

    def _classify(node, try_stack):
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and node.value is not None:
            for name in _names_in(node.value):
                _mark_escape(name, node.lineno)
        elif isinstance(node, ast.Assign):
            if any(not isinstance(t, ast.Name) for t in node.targets):
                for name in _names_in(node.value):
                    _mark_escape(name, node.lineno)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)\
                    and f.value.id in tracked:
                s = tracked[f.value.id]
                if f.attr in _CLOSERS:
                    s.closed = True
                elif f.attr in _RISKY_USES:
                    s.risky.append((node.lineno, f.attr, list(try_stack)))
                return
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for name in _names_in(arg):
                    _mark_escape(name, node.lineno)

    for stmt in fn.body:
        visit(stmt, [])

    for name, s in tracked.items():
        if s.closure_escape:
            continue
        if s.escape_line is None and not s.closed:
            emit("fd-no-closer", s.line,
                 f"fd/socket '{name}' created in {fn.name} is never "
                 "closed and never escapes")
            continue
        for line, attr, try_stack in s.risky:
            if s.escape_line is not None and s.escape_line <= line:
                continue  # ownership already transferred
            if any(_try_closes(t, name) for t in try_stack):
                continue
            emit("fd-use-unguarded", line,
                 f"'{name}.{attr}()' can raise out of {fn.name} before "
                 f"ownership of '{name}' transfers, with no enclosing "
                 "try closing it (dial-failure fd leak)")


def _try_closes(try_node: ast.Try, name: str) -> bool:
    bodies = list(try_node.finalbody)
    for h in try_node.handlers:
        bodies.extend(h.body)
    for stmt in bodies:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == name \
                    and node.func.attr in _CLOSERS:
                return True
    return False


# ---------------- threads ----------------


def _scan_threads(fn, emit, module_tree):
    for node in _walk_shallow(fn):
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
            continue
        daemon = None
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        if daemon:
            continue
        # Bound somewhere with a join (or daemon flip) in module reach?
        owner = _thread_owner(fn, node)
        if owner is not None and _has_join(module_tree, owner):
            continue
        emit("unjoined-thread", node.lineno,
             f"non-daemon Thread created in {fn.name} without a .join() "
             "owner (or daemon=True)")


def _thread_owner(fn, call) -> str | None:
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and node.value is call:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Attribute):
                return ast.unparse(t)
    return None


def _has_join(module_tree, owner: str) -> bool:
    # A join/daemon-set on the owner anywhere in the module counts as a
    # registered owner (e.g. created in __init__, joined in close()).
    for node in ast.walk(module_tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join" \
                and ast.unparse(node.func.value) == owner:
            return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                        and ast.unparse(t.value) == owner:
                    return True
    return False
