"""Baseline workflow: existing debt is recorded, new violations fail.

The mechanics (line-number-free fingerprints, multiset matching,
`--update-baseline`, stale-entry warnings) live in tools.checklib and are
shared with tools.graphcheck; this module pins staticcheck's baseline
location and keeps the long-standing load/save/diff API.
"""

from __future__ import annotations

from tools.checklib import (diff_baseline as diff,  # noqa: F401
                            load_baseline as load,
                            save_baseline as save)

BASELINE_REL = "tools/staticcheck/baseline.json"
