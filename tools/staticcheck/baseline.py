"""Baseline workflow: existing debt is recorded, new violations fail.

The baseline file is a JSON list of {rule, path, detail} entries —
line-number-free fingerprints, so routine edits above a recorded site do
not churn it. Matching is multiset-aware: two identical recorded entries
absorb two identical findings; a third is NEW and fails the run.

`python -m tools.staticcheck --update-baseline` rewrites the file from
the current findings (the reviewed way to accept debt); stale entries
(recorded but no longer firing) are reported as warnings and dropped on
the next update, so the debt ledger only ever shrinks by paying it.
"""

from __future__ import annotations

import collections
import json
import os

from tools.staticcheck import Finding

BASELINE_REL = "tools/staticcheck/baseline.json"


def load(path: str) -> collections.Counter:
    if not os.path.exists(path):
        return collections.Counter()
    with open(path) as f:
        entries = json.load(f)
    return collections.Counter(
        (e["rule"], e["path"], e["detail"]) for e in entries)


def save(path: str, findings: list) -> None:
    entries = sorted(
        ({"rule": f.rule, "path": f.path, "detail": f.detail}
         for f in findings),
        key=lambda e: (e["rule"], e["path"], e["detail"]))
    with open(path, "w") as f:
        json.dump(entries, f, indent=1)
        f.write("\n")


def diff(findings: list, baseline: collections.Counter):
    """-> (new findings, stale baseline keys)."""
    remaining = collections.Counter(baseline)
    new: list[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        if remaining[f.key()] > 0:
            remaining[f.key()] -= 1
        else:
            new.append(f)
    stale = sorted(k for k, n in remaining.items() if n > 0)
    return new, stale
