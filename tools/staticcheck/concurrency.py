"""Pass 2 — blocking calls under locks + lock-acquisition-order cycles.

AST-based, over the hot-plane core modules. Two rule families:

`blocking-under-lock`: a call that can block indefinitely (socket
send/recv/connect, subprocess, time.sleep, future .result()/.join(),
payload pickling, jax device ops) issued while a lock is held. Dedicated
send-serialization locks (send_lock / flush_lock / head_lock — they exist
precisely to serialize one socket's writes) permit SEND calls but nothing
else. `cv-wait-foreign-lock`: waiting on a condition variable while
holding a lock that is not the cv's own (wait() only releases its own
lock; everything else held stalls every contender).

`lock-order-cycle` / `relock`: a cross-module lock-acquisition graph.
Direct nesting adds held->acquired edges; one level of call resolution
(self.method, same-module function, corpus-unique method name) adds edges
for locks a callee acquires. A cycle = two code paths that can take the
same pair of locks in opposite orders; `relock` = syntactic re-entry of a
non-reentrant lock.

Lock identity is `Class.attr` (resolved via the corpus-wide registry of
`self.x = threading.Lock()` assignments; attribute receivers other than
`self` resolve when exactly one class defines that attr, else `?.attr`).
Intentional sites carry `# staticcheck: ok <rule>` inline.
"""

from __future__ import annotations

import ast
import os
import re

from tools.staticcheck import Finding

# The lock-heavy core planes the paper's L0/L1 substrate lives in, plus
# the train/tune/serve planes (PR 9 put real lock/thread/fd traffic into
# train's elastic checkpoint + watchdog paths).
TARGETS = (
    "ray_tpu/core/node_agent.py",
    "ray_tpu/core/head_shards.py",
    "ray_tpu/core/worker.py",
    "ray_tpu/core/runtime.py",
    "ray_tpu/core/object_store.py",
    "ray_tpu/core/objxfer.py",
    "ray_tpu/core/task_events.py",
    "ray_tpu/train/backend.py",
    "ray_tpu/train/checkpoint.py",
    "ray_tpu/train/session.py",
    "ray_tpu/train/step.py",
    "ray_tpu/train/trainer.py",
    "ray_tpu/tune/schedulers.py",
    "ray_tpu/tune/search.py",
    "ray_tpu/tune/tuner.py",
    "ray_tpu/llm/serve.py",
    # The data plane (PR 15): the streaming executor's memory-budget lock
    # + the datasource/file IO paths had never been scanned.
    "ray_tpu/data/execution.py",
    "ray_tpu/data/dataset.py",
    "ray_tpu/data/datasource.py",
    "ray_tpu/data/avro.py",
    "ray_tpu/data/tfrecord.py",
    "ray_tpu/data/preprocessors.py",
    # Multi-tenant plane (PR 20): the job ledger's quota lock sits inside
    # the grant path, the autoscaler reconciler calls into the runtime
    # under its own loop, and the job supervisor juggles child-process
    # pipes — all three are lock/fd territory.
    "ray_tpu/core/jobs.py",
    "ray_tpu/autoscaler/__init__.py",
    "ray_tpu/autoscaler/policy.py",
    "ray_tpu/job_submission.py",
)

SEND_LOCKS = {"send_lock", "flush_lock", "head_lock"}

SEND_METHODS = {"sendall", "sendmsg", "sendto", "send"}
ALWAYS_BLOCKING_METHODS = {
    "recv", "recv_into", "recvfrom", "accept", "connect", "communicate",
    "result", "join", "sleep",
}
SEND_FUNCS = {"send_msg", "send_many", "sendmsg_all"}
BLOCKING_FUNCS = {
    "dial", "create_connection", "fetch_from_peer", "build_binary",
    "build_native",
}
PICKLE_BASES = {"pickle", "cloudpickle", "_pickle"}
PICKLE_METHODS = {"dumps", "loads", "dump", "load"}
PAYLOAD_PICKLE_FUNCS = {"serialize_value"}
JAX_METHODS = {"device_put", "block_until_ready", "device_get"}
SUBPROCESS_FUNCS = {"run", "Popen", "check_call", "check_output", "call"}

_LOCKY = re.compile(r"(lock|mutex|_cv$|^cv$|cond)")


def _is_str_or_path_join(f, node) -> bool:
    """os.path.join(...) and "sep".join(...) are not thread joins: a
    string-literal receiver, a receiver chain mentioning path, or >=2
    positional args (Thread.join takes at most a timeout)."""
    if isinstance(f.value, ast.Constant):
        return True
    if "path" in _expr_src(f.value):
        return True
    return len(node.args) >= 2


def _lock_like(name: str) -> bool:
    return bool(_LOCKY.search(name.lower()))


def suppressed(lines: list, lineno: int, rule: str) -> bool:
    """`# staticcheck: ok <rule>` markers (shared impl in checklib)."""
    from tools.checklib import suppressed as _supp
    return _supp(lines, lineno, rule, tool="staticcheck")


# ---------------- corpus model ----------------


class _Module:
    def __init__(self, root: str, rel: str):
        self.rel = rel
        self.name = os.path.basename(rel).removesuffix(".py")
        with open(os.path.join(root, rel)) as f:
            src = f.read()
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=rel)
        # {class: {method: FunctionDef}}, {func: FunctionDef}
        self.classes: dict[str, dict] = {}
        self.functions: dict[str, ast.AST] = {}
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = {
                    n.name: n for n in node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node


class _Corpus:
    def __init__(self, modules: list):
        self.modules = modules
        # lock attr -> {(class name, kind)} from `self.x = threading.X()`
        self.attr_owners: dict[str, set] = {}
        # method name -> [(module, class, FunctionDef)]
        self.methods: dict[str, list] = {}
        for m in modules:
            for cname, meths in m.classes.items():
                for mname, fn in meths.items():
                    self.methods.setdefault(mname, []).append(
                        (m, cname, fn))
                for fn in meths.values():
                    self._scan_lock_defs(fn, cname)

    def _scan_lock_defs(self, fn, cname: str):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            kind = _lock_ctor_kind(node.value)
            if kind is None:
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    self.attr_owners.setdefault(t.attr, set()).add(
                        (cname, kind))

    def owner_of(self, attr: str):
        owners = self.attr_owners.get(attr, set())
        if len(owners) == 1:
            return next(iter(owners))
        return None


def _lock_ctor_kind(value) -> str | None:
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    return name if name in ("Lock", "RLock", "Condition") else None


class _Lock:
    def __init__(self, identity: str, attr: str, kind: str | None,
                 expr_src: str):
        self.identity = identity
        self.attr = attr
        self.kind = kind        # Lock | RLock | Condition | None (unknown)
        self.expr_src = expr_src
        self.is_send = attr in SEND_LOCKS


def _expr_src(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — display only
        return "<expr>"


def _lock_of_expr(expr, corpus: _Corpus, cname: str | None):
    """The _Lock a with-item / wait receiver denotes, or None."""
    if isinstance(expr, ast.Attribute):
        attr = expr.attr
        if not _lock_like(attr):
            return None
        kind = None
        if (isinstance(expr.value, ast.Name) and expr.value.id == "self"
                and cname is not None
                and any(o[0] == cname
                        for o in corpus.attr_owners.get(attr, ()))):
            owner = cname
            kind = next(k for c, k in corpus.attr_owners[attr]
                        if c == cname)
        else:
            resolved = corpus.owner_of(attr)
            if resolved is not None:
                owner, kind = resolved
            else:
                owner = "?"
        return _Lock(f"{owner}.{attr}", attr, kind, _expr_src(expr))
    if isinstance(expr, ast.Name):
        if not _lock_like(expr.id):
            return None
        return _Lock(f"<local>.{expr.id}", expr.id, None, expr.id)
    if isinstance(expr, ast.Subscript):
        key = expr.slice
        if isinstance(key, ast.Constant) and isinstance(key.value, str) \
                and _lock_like(key.value):
            return _Lock(f"?.{key.value}", key.value, None,
                         _expr_src(expr))
    return None


# ---------------- the walker ----------------


class _FuncWalker:
    """Walks one function body tracking the held-lock stack; emits
    blocking-call findings and acquisition edges."""

    def __init__(self, corpus: _Corpus, module: _Module,
                 cname: str | None, qualname: str,
                 edges: list, findings: list):
        self.corpus = corpus
        self.module = module
        self.cname = cname
        self.qualname = qualname
        self.edges = edges          # (from_id, to_id, site, via)
        self.findings = findings
        self.held: list[_Lock] = []

    # -- entry --

    def walk(self, fn):
        for stmt in fn.body:
            self._stmt(stmt)

    def _stmt(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested def: runs later with its own (empty) lock context.
            _FuncWalker(self.corpus, self.module, self.cname,
                        f"{self.qualname}.{node.name}", self.edges,
                        self.findings).walk(node)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            self._with(node)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)
            elif isinstance(child, (ast.excepthandler,)):
                for s in child.body:
                    self._stmt(s)

    def _with(self, node):
        pushed = 0
        for item in node.items:
            self._expr(item.context_expr, is_with_ctx=True)
            lk = _lock_of_expr(item.context_expr, self.corpus, self.cname)
            if lk is not None:
                for held in self.held:
                    self.edges.append(
                        (held, lk, (self.module, node.lineno,
                                    self.qualname), "nest"))
                if any(h.identity == lk.identity for h in self.held) \
                        and lk.kind == "Lock":
                    self._finding(
                        "relock", node.lineno,
                        f"re-entering non-reentrant {lk.identity} "
                        f"already held in {self.qualname}")
                self.held.append(lk)
                pushed += 1
        for stmt in node.body:
            self._stmt(stmt)
        for _ in range(pushed):
            self.held.pop()

    # -- expressions / calls --

    def _expr(self, node, is_with_ctx: bool = False):
        for call in [n for n in ast.walk(node) if isinstance(n, ast.Call)]:
            self._call(call)

    def _call(self, node: ast.Call):
        if not self.held:
            self._call_edges(node)
            return
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else None
        base = None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            base = f.value.id
        name = f.id if isinstance(f, ast.Name) else None

        label = None
        is_send = False
        if attr in ("wait", "wait_for"):
            self._wait(node, f)
        elif attr in SEND_METHODS or name in SEND_FUNCS \
                or attr in SEND_FUNCS:
            label, is_send = f"socket send ({attr or name})", True
        elif attr == "join" and not _is_str_or_path_join(f, node):
            label = "blocking call (.join())"
        elif attr in ALWAYS_BLOCKING_METHODS and attr != "join":
            label = f"blocking call (.{attr}())"
        elif base in PICKLE_BASES and attr in PICKLE_METHODS:
            label = f"payload pickling ({base}.{attr})"
        elif (attr in PAYLOAD_PICKLE_FUNCS
              or name in PAYLOAD_PICKLE_FUNCS):
            label = f"payload pickling ({attr or name})"
        elif attr in JAX_METHODS:
            label = f"jax device op (.{attr})"
        elif base == "subprocess" and attr in SUBPROCESS_FUNCS:
            label = f"subprocess ({attr})"
        elif name in BLOCKING_FUNCS or attr in BLOCKING_FUNCS:
            label = f"blocking call ({attr or name})"

        if label is not None:
            # Send calls are the one thing a dedicated send lock is FOR.
            blockers = [h for h in self.held
                        if not (is_send and h.is_send)]
            if blockers:
                self._finding(
                    "blocking-under-lock", node.lineno,
                    f"{label} under {blockers[-1].identity} in "
                    f"{self.qualname}")
        self._call_edges(node)

    def _wait(self, node, f):
        recv = _lock_of_expr(f.value, self.corpus, self.cname)
        if recv is None:
            if self.held:  # Event/proc/future .wait under a lock
                self._finding(
                    "blocking-under-lock", node.lineno,
                    f"blocking call (.{f.attr}()) under "
                    f"{self.held[-1].identity} in {self.qualname}")
            return
        foreign = [h for h in self.held if h.identity != recv.identity]
        if foreign:
            self._finding(
                "cv-wait-foreign-lock", node.lineno,
                f"{recv.expr_src}.{f.attr}() waits while holding "
                f"{foreign[-1].identity} in {self.qualname} (wait only "
                "releases its own lock)")

    # -- one-level call resolution for the order graph --

    def _call_edges(self, node: ast.Call):
        if not self.held:
            return
        target = self._resolve(node.func)
        if target is None:
            return
        tmod, tcls, tfn, via = target
        for lk, line in _acquired_locks(tfn, self.corpus, tcls):
            for held in self.held:
                if held.identity == lk.identity:
                    if via == "self" and lk.kind == "Lock":
                        # Same instance, non-reentrant: the callee will
                        # block on the lock this caller already holds.
                        self._finding(
                            "relock", node.lineno,
                            f"call to {tcls}.{tfn.name} (which takes "
                            f"{lk.identity}) while {self.qualname} "
                            "already holds it")
                    continue
                self.edges.append(
                    (held, lk, (self.module, node.lineno, self.qualname),
                     via))

    def _resolve(self, f):
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id == "self" and self.cname is not None:
                fn = self.module.classes.get(self.cname, {}).get(f.attr)
                if fn is not None:
                    return (self.module, self.cname, fn, "self")
            cands = self.corpus.methods.get(f.attr, [])
            if len(cands) == 1:
                m, c, fn = cands[0]
                return (m, c, fn, "unique")
        elif isinstance(f, ast.Name):
            fn = self.module.functions.get(f.id)
            if fn is not None:
                return (self.module, None, fn, "module")
        return None

    def _finding(self, rule: str, lineno: int, detail: str):
        if suppressed(self.module.lines, lineno, rule):
            return
        self.findings.append(
            Finding(rule, self.module.rel, lineno, detail))


def _acquired_locks(fn, corpus: _Corpus, cname: str | None) -> list:
    """Locks a function body acquires directly (nested defs excluded —
    they run in their own context later)."""
    out = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    lk = _lock_of_expr(item.context_expr, corpus, cname)
                    if lk is not None:
                        out.append((lk, child.lineno))
            visit(child)

    visit(fn)
    return out


# ---------------- cycles ----------------


def _find_cycles(edges: list) -> list:
    """SCCs with a cycle in the acquisition graph -> findings. Send locks
    are leaves by construction (send_msg only wraps sendall) and unknown
    `?.x` identities collapse distinct objects, so both are excluded as
    cycle STARTS but kept as edges for reporting context."""
    graph: dict[str, set] = {}
    sites: dict[tuple, tuple] = {}
    for a, b, site, _via in edges:
        if a.identity == b.identity:
            continue
        graph.setdefault(a.identity, set()).add(b.identity)
        sites.setdefault((a.identity, b.identity), site)
    # Tarjan SCC
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    stack: list[str] = []
    on: set = set()
    sccs: list[list] = []
    counter = [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in graph.get(v, ()):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(comp)

    for v in list(graph):
        if v not in index:
            strongconnect(v)

    findings = []
    for comp in sccs:
        comp = sorted(comp)
        if all(c.startswith("?.") for c in comp):
            continue
        where = []
        for a, b in sites:
            if a in comp and b in comp:
                mod, line, qual = sites[(a, b)]
                where.append(f"{a}->{b} at {mod.rel} in {qual}")
        findings.append(Finding(
            "lock-order-cycle", where and sites[
                next((a, b) for a, b in sites
                     if a in comp and b in comp)][0].rel or "",
            0,
            "lock acquisition cycle: " + " | ".join(sorted(where))))
    return findings


# ---------------- entry ----------------


def run(root: str, targets: tuple | None = None) -> list:
    rels = [t for t in (targets or TARGETS)
            if os.path.exists(os.path.join(root, t))]
    modules = [_Module(root, rel) for rel in rels]
    corpus = _Corpus(modules)
    findings: list[Finding] = []
    edges: list = []
    for m in modules:
        for cname, meths in m.classes.items():
            for mname, fn in meths.items():
                _FuncWalker(corpus, m, cname, f"{cname}.{mname}",
                            edges, findings).walk(fn)
        for fname, fn in m.functions.items():
            _FuncWalker(corpus, m, None, fname, edges, findings).walk(fn)
    findings.extend(_find_cycles(edges))
    return findings
