"""Lightweight .proto parser: just enough proto3 for raytpu.proto.

Handles messages (nested), scalar/message/repeated fields, oneofs, and
map<k, v> fields (modeled as a field of the synthesized *Entry message,
wire type 2 — the layout both google.protobuf and the hand-rolled C++
codec put on the wire). No services/enums/extensions/reserved — the
schema has none; the parser FAILS LOUDLY on syntax it does not know
rather than silently skipping, so schema growth that outruns the checker
surfaces as a checker error, not a missed drift.
"""

from __future__ import annotations

import dataclasses
import re

# proto scalar type -> wire type (proto3; no packed numeric repeated in
# this schema, but packed(2) is accepted for them at the comparison layer)
SCALAR_WIRE = {
    "int32": 0, "int64": 0, "uint32": 0, "uint64": 0,
    "sint32": 0, "sint64": 0, "bool": 0, "enum": 0,
    "fixed64": 1, "sfixed64": 1, "double": 1,
    "fixed32": 5, "sfixed32": 5, "float": 5,
    "string": 2, "bytes": 2,
}


@dataclasses.dataclass
class Field:
    name: str
    number: int
    type: str          # scalar name, "map", or message type name
    repeated: bool
    oneof: str | None = None

    @property
    def wire_type(self) -> int:
        if self.type in SCALAR_WIRE:
            return SCALAR_WIRE[self.type]
        return 2  # message / map / unknown-named type

    @property
    def is_message(self) -> bool:
        return self.type not in SCALAR_WIRE and self.type != "map"


@dataclasses.dataclass
class Message:
    full_name: str                      # e.g. "RegisterNode.WorkerInventory"
    fields: dict = dataclasses.field(default_factory=dict)  # name -> Field

    def by_number(self) -> dict:
        return {f.number: f for f in self.fields.values()}


_TOKEN = re.compile(r"""
    (?P<comment>//[^\n]*|/\*.*?\*/)
  | (?P<word>[A-Za-z_][\w.]*)
  | (?P<number>\d+)
  | (?P<punct>[{}<>=;,])
  | (?P<string>"[^"]*")
  | (?P<ws>\s+)
""", re.VERBOSE | re.DOTALL)


def _tokens(text: str):
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            raise ValueError(f"protoparse: cannot tokenize at {text[pos:pos+40]!r}")
        pos = m.end()
        if m.lastgroup in ("comment", "ws"):
            continue
        yield m.group()


class _Stream:
    def __init__(self, toks):
        self.toks = list(toks)
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self):
        t = self.peek()
        if t is None:
            raise ValueError("protoparse: unexpected end of file")
        self.i += 1
        return t

    def expect(self, tok):
        t = self.next()
        if t != tok:
            raise ValueError(f"protoparse: expected {tok!r}, got {t!r}")
        return t


def parse(path: str) -> dict:
    """Parse a .proto file -> {full_message_name: Message}."""
    with open(path) as f:
        text = f.read()
    s = _Stream(_tokens(text))
    messages: dict[str, Message] = {}
    while s.peek() is not None:
        t = s.next()
        if t in ("syntax", "package"):
            while s.next() != ";":
                pass
        elif t == "option":
            while s.next() != ";":
                pass
        elif t == "import":
            while s.next() != ";":
                pass
        elif t == "message":
            _parse_message(s, prefix="", messages=messages)
        else:
            raise ValueError(f"protoparse: unknown top-level token {t!r}")
    return messages


def _parse_message(s: _Stream, prefix: str, messages: dict):
    name = s.next()
    full = f"{prefix}{name}"
    msg = Message(full)
    messages[full] = msg
    s.expect("{")
    _parse_body(s, msg, full, messages, oneof=None)


def _parse_body(s: _Stream, msg: Message, full: str, messages: dict,
                oneof: str | None):
    while True:
        t = s.next()
        if t == "}":
            return
        if t == ";":
            continue
        if t == "message":
            if oneof is not None:
                raise ValueError("protoparse: message inside oneof")
            _parse_message(s, prefix=f"{full}.", messages=messages)
            continue
        if t == "oneof":
            oname = s.next()
            s.expect("{")
            _parse_body(s, msg, full, messages, oneof=oname)
            continue
        if t == "reserved":
            while s.next() != ";":
                pass
            continue
        # field: [repeated] <type> <name> = <number> ;
        repeated = False
        if t == "repeated":
            repeated = True
            t = s.next()
        if t == "map":
            s.expect("<")
            ktype = s.next()
            s.expect(",")
            vtype = s.next()
            s.expect(">")
            fname = s.next()
            s.expect("=")
            num = int(s.next())
            s.expect(";")
            msg.fields[fname] = Field(fname, num, "map", repeated=True,
                                      oneof=oneof)
            # Synthesize the map entry message (what rides the wire).
            entry = Message(f"{full}.{fname}#entry")
            entry.fields["key"] = Field("key", 1, ktype, False)
            entry.fields["value"] = Field("value", 2, vtype, False)
            messages[entry.full_name] = entry
            continue
        ftype = t
        fname = s.next()
        s.expect("=")
        num = int(s.next())
        s.expect(";")
        msg.fields[fname] = Field(fname, num, ftype, repeated, oneof=oneof)
