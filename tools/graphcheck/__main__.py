"""CLI: `python -m tools.graphcheck [--update-baseline] [--graphs PAT]`.

Exit codes: 0 clean (all findings covered by the baseline and every
fingerprint matches), 1 new violations/drift, 2 usage/internal error.
`--update-baseline` rewrites BOTH tools/graphcheck/baseline.json (the
findings debt ledger — kept empty for ray_tpu/) and fingerprints.json
(the per-graph contract).
"""

from __future__ import annotations

import argparse
import fnmatch
import os
import sys


def main(argv=None) -> int:
    # Simulated-mesh environment must be pinned before jax touches a
    # backend (jax may already be imported via sitecustomize; backends
    # initialize lazily, so the env + config update still land).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms",
                      os.environ["JAX_PLATFORMS"].split(",")[0])

    from tools import checklib
    from tools import graphcheck
    from tools.graphcheck import fingerprint, lowering

    p = argparse.ArgumentParser(
        prog="python -m tools.graphcheck",
        description="XLA-graph static analysis: donation, host-sync, "
                    "recompile, collective/sharding drift, memory gates "
                    "over every registered TPU hot graph")
    p.add_argument("--graphs", default=None,
                   help="fnmatch pattern over registered graph names "
                        "(fingerprint cover checks are skipped when "
                        "filtered)")
    p.add_argument("--root", default=checklib.repo_root())
    p.add_argument("--baseline", default=None)
    p.add_argument("--fingerprints", default=None)
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignore the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept current findings + fingerprints")
    p.add_argument("--list", action="store_true",
                   help="list registered graphs and exit")
    args = p.parse_args(argv)

    registry = graphcheck.load_corpus()
    if args.graphs:
        registry = {k: v for k, v in registry.items()
                    if fnmatch.fnmatch(k, args.graphs)}
        if not registry:
            print(f"no registered graph matches {args.graphs!r}",
                  file=sys.stderr)
            return 2
    if args.list:
        for name, reg in sorted(registry.items()):
            meshes = ", ".join(graphcheck.mesh_key(m) for m in reg.meshes)
            print(f"{name}  [{meshes}]  ({reg.source[0]}:{reg.source[1]})")
        return 0

    fpath = args.fingerprints or os.path.join(
        args.root, graphcheck.FINGERPRINTS_REL)
    bpath = args.baseline or os.path.join(args.root,
                                          graphcheck.BASELINE_REL)
    corpus = lowering.lower_all(registry)
    for rec in corpus:
        print(f"lowered {rec.graph_id}", file=sys.stderr)

    if args.update_baseline:
        fps = graphcheck.current_fingerprints(corpus)
        if args.graphs:
            merged = fingerprint.load(fpath)
            merged.update(fps)
            fps = merged
        fingerprint.save(fpath, fps)
        print(f"fingerprints updated: {len(fps)} graphs -> {fpath}")
        findings = graphcheck.run(args.root, corpus=corpus,
                                  fingerprints_path=fpath)
        checklib.save_baseline(bpath, findings)
        print(f"baseline updated: {len(findings)} entries -> {bpath}")
        return 0

    findings = graphcheck.run(args.root, corpus=corpus,
                              fingerprints_path=fpath)
    if args.graphs:
        # A filtered run cannot see the whole corpus; cover checks would
        # misfire as stale.
        findings = [f for f in findings if f.rule != "fingerprint-stale"]
    return checklib.report(findings, bpath,
                           use_baseline=not args.no_baseline)


if __name__ == "__main__":
    sys.exit(main())
