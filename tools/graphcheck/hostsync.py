"""Finding class 2 — host-sync ops inside steady-state hot graphs.

Graph side: `pure_callback` / `io_callback` / `debug_callback`
(jax.debug.print lowers to it) primitives in the jaxpr, cross-checked
against callback custom_calls in the StableHLO — each one is a device→
host round trip serialized into the jitted region. Graphs registered
with hot=True fail on any; warm-path graphs (hot=False) just carry the
count in their fingerprint so an increase is still drift.

AST companion (`host-sync-coercion`): python-scalar coercions on traced
values at jit sites — `float(x)` / `int(x)` / `bool(x)` / `x.item()` on
a traced parameter, or branching on one (`if x:`) — each forces a
blocking device_get (or a TracerBoolConversionError at trace time the
moment someone jits the caller). Only BARE parameter names of functions
that are demonstrably jit targets in the same module are flagged, so
config/static params named like configs stay quiet.
"""

from __future__ import annotations

import ast
import os

from tools.checklib import Finding, suppressed
from tools.graphcheck.lowering import LoweredGraph

CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback"}

# Parameters that carry statics/configs by repo convention — never traced.
_STATIC_NAMES = {"config", "cfg", "c", "self", "mesh", "module", "tx",
                 "optimizer", "rules", "key_shape"}


def _count_jaxpr_callbacks(jaxpr) -> int:
    seen = 0
    stack = [jaxpr.jaxpr]
    while stack:
        jx = stack.pop()
        for eqn in jx.eqns:
            if eqn.primitive.name in CALLBACK_PRIMS:
                seen += 1
            for v in eqn.params.values():
                for w in (v if isinstance(v, (list, tuple)) else (v,)):
                    inner = getattr(w, "jaxpr", None)
                    if inner is not None:
                        stack.append(inner)
    return seen


def analyze(rec: LoweredGraph) -> tuple:
    """-> (callback count for the fingerprint, findings)."""
    n = _count_jaxpr_callbacks(rec.jaxpr)
    # StableHLO cross-check catches callbacks smuggled in below the jaxpr
    # (custom lowering rules).
    n_hlo = rec.stablehlo.count("callback")
    count = max(n, 1 if (n == 0 and n_hlo) else n)
    findings: list[Finding] = []
    if rec.spec.hot and count:
        path, line = rec.spec.source
        findings.append(Finding(
            "host-sync", path, line,
            f"{rec.graph_id}: {count} host callback(s) "
            "(pure_callback/io_callback/debug_print) inside a graph "
            "registered as steady-state hot — each is a device->host "
            "sync serialized into the step"))
    return count, findings


# ---------------- AST companion ----------------


def _jit_target_names(tree: ast.Module) -> tuple:
    """-> (jit-target function names, {name: kwargs bound statically}).

    A name counts as a jit target when it is passed to jax.jit somewhere
    in the module (directly, via functools.partial(fn, ...), or as a jit
    decorator). Kwargs bound by ANY `partial(fn, kw=...)` in the module,
    and names in literal `static_argnames`, are python statics at trace
    time — never traced — so the coercion rules must skip them."""
    targets: set[str] = set()
    static_kwargs: dict[str, set] = {}

    def is_jit(func) -> bool:
        return (isinstance(func, ast.Attribute) and func.attr == "jit") \
            or (isinstance(func, ast.Name) and func.id == "jit")

    def is_partial(func) -> bool:
        return (isinstance(func, ast.Name) and func.id == "partial") or \
            (isinstance(func, ast.Attribute) and func.attr == "partial")

    def first_fn_name(node):
        # jax.jit(X) / jax.jit(partial(X, ...)) -> X's name
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Call) and is_partial(node.func):
            return first_fn_name(node.args[0]) if node.args else None
        return None

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if is_partial(node.func) and node.args:
            name = first_fn_name(node.args[0])
            if name:
                static_kwargs.setdefault(name, set()).update(
                    kw.arg for kw in node.keywords if kw.arg)
        if is_jit(node.func) and node.args:
            name = first_fn_name(node.args[0])
            if name:
                targets.add(name)
                for kw in node.keywords:
                    if kw.arg == "static_argnames":
                        v = kw.value
                        elts = v.elts if isinstance(
                            v, (ast.Tuple, ast.List)) else [v]
                        static_kwargs.setdefault(name, set()).update(
                            e.value for e in elts
                            if isinstance(e, ast.Constant))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                if is_jit(d):
                    targets.add(node.name)
                elif isinstance(dec, ast.Call) and any(
                        is_jit(a) for a in dec.args):
                    # @functools.partial(jax.jit, static_argnames=...)
                    targets.add(node.name)
                    for kw in dec.keywords:
                        if kw.arg == "static_argnames":
                            v = kw.value
                            elts = v.elts if isinstance(
                                v, (ast.Tuple, ast.List)) else [v]
                            static_kwargs.setdefault(
                                node.name, set()).update(
                                e.value for e in elts
                                if isinstance(e, ast.Constant))
    return targets, static_kwargs


def scan_sources(root: str, rels: tuple) -> list:
    findings: list[Finding] = []
    for rel in rels:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue
        with open(path) as f:
            src = f.read()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        targets, static_kwargs = _jit_target_names(tree)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name not in targets:
                continue
            traced = {a.arg for a in node.args.args
                      + node.args.posonlyargs}
            traced -= _STATIC_NAMES
            traced -= {a.arg for a in node.args.kwonlyargs}
            traced -= static_kwargs.get(node.name, set())
            for f in _scan_fn(node, traced, rel):
                if not suppressed(lines, f.line, f.rule,
                                  tool="graphcheck"):
                    findings.append(f)
    return findings


def _scan_fn(fn, traced: set, rel: str) -> list:
    out: list[Finding] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in ("float", "int",
                                                    "bool") \
                    and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in traced:
                out.append(Finding(
                    "host-sync-coercion", rel, node.lineno,
                    f"{f.id}({node.args[0].id}) coerces traced value "
                    f"'{node.args[0].id}' to a python scalar inside jit "
                    f"target {fn.name} (device sync / trace error)"))
            elif isinstance(f, ast.Attribute) and f.attr == "item" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in traced:
                out.append(Finding(
                    "host-sync-coercion", rel, node.lineno,
                    f"{f.value.id}.item() on traced value inside jit "
                    f"target {fn.name}"))
        elif isinstance(node, (ast.If, ast.While)) \
                and isinstance(node.test, ast.Name) \
                and node.test.id in traced:
            out.append(Finding(
                "host-sync-coercion", rel, node.lineno,
                f"branching on traced value '{node.test.id}' inside jit "
                f"target {fn.name} (implicit bool() -> device sync)"))
    return out
