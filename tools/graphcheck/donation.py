"""Finding class 1 — donation.

Two failure shapes, both invisible at runtime on CPU:

`donation-missing` — a large buffer the graph THREADS (an input whose
shape/dtype reappears in the outputs: TrainState, KV pools, optimizer
moments) accepted by value but not donated. XLA then keeps the input
alive across the step, doubling that buffer's HBM footprint.

`donation-rejected` — `donate_argnums` was passed but XLA could not use
the donation (dtype/shape/sharding mismatch between the donated input and
every output). jax only WARNS — the jit runs fine, the donation is a
silent no-op — so the warning is promoted to a gate failure here.

`lowering-failed` also lives here: a registered graph that no longer
lowers/compiles at all is the loudest drift of the lot.
"""

from __future__ import annotations

import collections

from tools.checklib import Finding
from tools.graphcheck.lowering import LoweredGraph


def _key(aval):
    return (tuple(aval.shape), str(aval.dtype))


def analyze(rec: LoweredGraph) -> list:
    spec = rec.spec
    path, line = spec.source
    findings: list[Finding] = []
    if rec.error is not None:
        findings.append(Finding(
            "lowering-failed", path, line,
            f"{rec.graph_id}: graph no longer compiles: {rec.error}"))

    out_counts = collections.Counter(_key(a) for a in rec.flat_out_avals)
    # Donated inputs absorb their congruent outputs first, so a donated
    # state leaf does not leave its output free to "absolve" an identical
    # un-donated leaf.
    for fa in rec.flat_in:
        if fa.donated and out_counts[_key(fa.aval)] > 0:
            out_counts[_key(fa.aval)] -= 1
    for fa in rec.flat_in:
        if fa.donated:
            continue
        size = int(fa.aval.size) * fa.aval.dtype.itemsize
        if size < spec.min_donate_bytes:
            continue
        if out_counts[_key(fa.aval)] > 0:
            out_counts[_key(fa.aval)] -= 1
            findings.append(Finding(
                "donation-missing", path, line,
                f"{rec.graph_id}: {fa.label} "
                f"({size} bytes {fa.aval.dtype}{list(fa.aval.shape)}) is "
                "threaded through the step (congruent output) but not in "
                "donate_argnums — its HBM is held twice"))
    for msg in rec.donation_warnings:
        findings.append(Finding(
            "donation-rejected", path, line,
            f"{rec.graph_id}: XLA rejected a declared donation "
            f"(silent no-op): {msg}"))
    # Registered intent vs what the production wrapper actually lowered:
    # donate_argnums declared here but ZERO aliased outputs in the
    # StableHLO (and no rejection warning) means the jit site itself
    # dropped the donation.
    if spec.donate_argnums and not rec.donation_warnings \
            and "tf.aliasing_output" not in rec.stablehlo:
        findings.append(Finding(
            "donation-missing", path, line,
            f"{rec.graph_id}: args {tuple(spec.donate_argnums)} are "
            "registered as donated but the lowered module aliases no "
            "output — the jit site dropped donate_argnums"))
    return findings


def donated_labels(rec: LoweredGraph) -> list:
    """Top-level donated arg labels for the fingerprint (collapsed to the
    argument, not every leaf)."""
    names = {}
    for fa in rec.flat_in:
        if fa.donated:
            names[fa.arg_idx] = fa.label.split("[")[0].split(".")[0]
    return [names[i] for i in sorted(names)]
