"""Finding class 3 — recompile hazards.

Graph side (`weak-type-input`): a registered graph whose example inputs
carry weak types. A weak-typed aval means the call site fed a bare
python scalar; jax keys the executable cache on weak_type, so the same
graph called once with `0.1` and once with `jnp.float32(0.1)` compiles
TWICE — the classic "why is decode recompiling every other step".

Source side (AST over the hook modules):

  jit-per-call          `jax.jit(f)(x)` — the wrapper (and its whole
                        executable cache) is rebuilt on every call.
  jit-in-loop           `jax.jit(...)` constructed inside a for/while
                        body — same hazard, loop-shaped. The repo idiom
                        is the process-global `_shared_jit` cache.
  unstable-static-arg   a call site of a known static-arg jit wrapper
                        passing a freshly-constructed object (Call/dict/
                        list literal) in a static position: every call
                        builds a new key, and unless the type defines
                        stable __hash__/__eq__ the compile cache forks
                        per call.

The runtime half of this finding class is ray_tpu.diagnostics.jit_misses
(a process-global compile counter) asserted flat over steady-state steps
in the engine/train tests.
"""

from __future__ import annotations

import ast
import os

from tools.checklib import Finding, suppressed
from tools.graphcheck.lowering import LoweredGraph


def analyze(rec: LoweredGraph) -> list:
    findings: list[Finding] = []
    path, line = rec.spec.source
    weak = [v for v in rec.jaxpr.jaxpr.invars
            if getattr(v.aval, "weak_type", False)]
    if weak:
        labels = [fa.label for fa, v in zip(rec.flat_in,
                                            rec.jaxpr.jaxpr.invars)
                  if getattr(v.aval, "weak_type", False)]
        findings.append(Finding(
            "weak-type-input", path, line,
            f"{rec.graph_id}: {len(weak)} weak-typed input(s) "
            f"({', '.join(labels[:4])}) — a python scalar fed as a "
            "traced arg forks the compile cache (weak vs strong dtype)"))
    return findings


# ---------------- AST pass ----------------


def _is_jit(func) -> bool:
    return (isinstance(func, ast.Attribute) and func.attr == "jit") \
        or (isinstance(func, ast.Name) and func.id == "jit")


def _static_names(call: ast.Call) -> tuple:
    """static_argnames of a jax.jit(...) call, when literal."""
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant))
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
    return ()


def _fresh_object(node) -> str | None:
    """An expression that constructs a new object per call: a Call, or a
    dict/list/set literal."""
    if isinstance(node, ast.Call):
        try:
            return ast.unparse(node.func)
        except Exception:  # noqa: BLE001 — display only
            return "<call>"
    if isinstance(node, (ast.Dict, ast.List, ast.Set)):
        return type(node).__name__.lower() + " literal"
    return None


def scan_sources(root: str, rels: tuple) -> list:
    findings: list[Finding] = []
    for rel in rels:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue
        with open(path) as f:
            src = f.read()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        for f_ in _scan_tree(tree, rel):
            if not suppressed(lines, f_.line, f_.rule, tool="graphcheck"):
                findings.append(f_)
    return findings


def _scan_tree(tree: ast.Module, rel: str) -> list:
    out: list[Finding] = []
    # name -> static argnames, for wrappers assigned at module/class level
    # (x = jax.jit(f, static_argnames=...)) and decorated defs.
    static_jits: dict[str, tuple] = {}

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call) \
                and _is_jit(node.value.func):
            names = _static_names(node.value)
            if names:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        static_jits[t.id] = names
                    elif isinstance(t, ast.Attribute):
                        static_jits[t.attr] = names
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    # @partial(jax.jit, static_argnames=...) or
                    # @jax.jit(static_argnames=...)
                    if _is_jit(dec.func) or any(_is_jit(a)
                                                for a in dec.args):
                        names = _static_names(dec)
                        if names:
                            static_jits[node.name] = names

    loop_stack: list = []

    def visit(node, in_loop: bool):
        if isinstance(node, ast.Call):
            if _is_jit(node.func):
                if in_loop:
                    out.append(Finding(
                        "jit-in-loop", rel, node.lineno,
                        "jax.jit(...) constructed inside a loop body — "
                        "rebuilds the wrapper (and its executable cache) "
                        "per iteration; hoist or use _shared_jit"))
            # jax.jit(f)(x): the jit call is itself the callee.
            if isinstance(node.func, ast.Call) and _is_jit(node.func.func):
                out.append(Finding(
                    "jit-per-call", rel, node.lineno,
                    "jax.jit(f)(...) builds a fresh wrapper per call — "
                    "every invocation retraces and recompiles"))
            callee = node.func
            cname = callee.id if isinstance(callee, ast.Name) else (
                callee.attr if isinstance(callee, ast.Attribute) else None)
            statics = static_jits.get(cname or "", ())
            for kw in node.keywords:
                if kw.arg in statics:
                    fresh = _fresh_object(kw.value)
                    if fresh:
                        out.append(Finding(
                            "unstable-static-arg", rel, node.lineno,
                            f"call to {cname} passes freshly-constructed "
                            f"{fresh} as static arg '{kw.arg}' — a new "
                            "cache key (likely a recompile) per call"))
        loop = in_loop or isinstance(node, (ast.For, ast.While,
                                            ast.AsyncFor))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # Nested defs run later, outside the loop's per-iteration
                # path... unless they are immediately called; keep simple
                # and scan them as non-loop bodies.
                visit(child, False)
            else:
                visit(child, loop)

    visit(tree, False)
    return out
