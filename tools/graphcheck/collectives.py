"""Finding class 4 — collective & sharding drift.

Collectives are counted per type in the COMPILED module (GSPMD inserts
them at partitioning time, after lowering — the StableHLO only carries
sharding annotations). The counts themselves are fingerprint material
(fingerprint.py): an edit that turns an FSDP param gather into a full
all-gather-per-layer changes the count and fails the gate without any
benchmark. Two findings fire directly here:

`replicated-param` — a flattened input leaf whose label matches the
spec's `expect_sharded` patterns lowered FULLY REPLICATED on a
multi-device mesh: the FSDP/TP sharding silently fell off (the memory
win is gone, and first use inserts an implicit broadcast).

`sharding-mismatch` — the sharding the graph actually lowered with
diverges from the spec DECLARED in parallel/sharding.py
(GraphSpec.declared_in_specs): someone edited the jit site without
updating the declared table, or vice versa.
"""

from __future__ import annotations

import re

from tools.checklib import Finding
from tools.graphcheck.lowering import LoweredGraph

COLLECTIVE_TYPES = ("all-gather", "all-reduce", "reduce-scatter",
                    "all-to-all", "collective-permute")

# Op definitions in HLO text: `%all-gather.3 = ...` or fused/async
# `all-gather-start`. `-done` halves of async pairs are not counted.
_OP_RE = re.compile(
    r"=\s*\S+\s+(all-gather|all-reduce|reduce-scatter|all-to-all|"
    r"collective-permute)(?:-start)?\(")


def count(hlo: str) -> dict:
    counts: dict[str, int] = {}
    for m in _OP_RE.finditer(hlo):
        counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


def _norm(spec) -> tuple:
    """PartitionSpec -> canonical tuple (trailing Nones trimmed)."""
    parts = [tuple(p) if isinstance(p, (tuple, list)) else p
             for p in tuple(spec)]
    while parts and parts[-1] is None:
        parts.pop()
    return tuple(parts)


def analyze(rec: LoweredGraph) -> tuple:
    """-> (collective counts for the fingerprint, findings)."""
    counts = count(rec.hlo) if rec.hlo else {}
    findings: list[Finding] = []
    spec = rec.spec
    path, line = spec.source

    multi = spec.mesh is not None and spec.mesh.devices.size > 1
    if multi and spec.expect_sharded and rec.input_shardings:
        for fa, sh in zip(rec.flat_in, rec.input_shardings):
            if not any(pat in fa.label for pat in spec.expect_sharded):
                continue
            if int(fa.aval.size) * fa.aval.dtype.itemsize < 128:
                continue
            if getattr(sh, "is_fully_replicated", False):
                findings.append(Finding(
                    "replicated-param", path, line,
                    f"{rec.graph_id}: {fa.label} is expected sharded "
                    f"({'/'.join(spec.expect_sharded)}) but lowered "
                    "fully replicated — the FSDP/TP sharding fell off"))

    if spec.declared_in_specs and rec.input_shardings:
        for pat, want in spec.declared_in_specs:
            matched = False
            for fa, got in zip(rec.flat_in, rec.input_shardings):
                if pat not in fa.label:
                    continue
                matched = True
                got_spec = getattr(got, "spec", None)
                if got_spec is None:
                    continue
                if _norm(want) != _norm(got_spec):
                    findings.append(Finding(
                        "sharding-mismatch", path, line,
                        f"{rec.graph_id}: {fa.label} lowered with "
                        f"{tuple(got_spec)} but the declared spec is "
                        f"{tuple(want)}"))
            if not matched:
                findings.append(Finding(
                    "sharding-mismatch", path, line,
                    f"{rec.graph_id}: declared spec pattern {pat!r} "
                    "matches no input — declaration drifted from the "
                    "graph"))
    return counts, findings
