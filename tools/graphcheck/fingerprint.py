"""The committed per-graph contract.

tools/graphcheck/fingerprints.json maps `<graph>@<mesh>` to the
fingerprint of its lowered+partitioned module:

  collectives   {type: count} from the compiled HLO
  donated       top-level donated argument labels
  callbacks     host callbacks in the jaxpr
  flops         cost_analysis() flops (4 significant digits)
  bytes         peak-memory estimate (4 significant digits)

ANY drift — a new collective, a dropped donation, an injected callback,
a flops/bytes step change — fails tier-1 until the change is reviewed
and `python -m tools.graphcheck --update-baseline` rewrites the file.
A registered graph missing from the file, or a committed entry whose
graph no longer registers, is drift too (the contract must cover the
corpus exactly).

flops/bytes are rounded to 4 significant digits: coarse enough to
absorb backend noise, fine enough that any real graph edit (a layer, a
gather, a dtype) moves them.
"""

from __future__ import annotations

import json
import os

from tools.checklib import Finding
from tools.graphcheck.lowering import LoweredGraph


def _sig4(x):
    if x is None:
        return None
    if x == 0:
        return 0
    from math import floor, log10
    ndig = 3 - floor(log10(abs(x)))
    return round(x, ndig) if ndig > 0 else int(round(x, ndig))


def build(rec: LoweredGraph, callbacks: int, coll_counts: dict,
          peak_bytes) -> dict:
    from tools.graphcheck import donation
    flops = None
    if rec.compiled is not None:
        try:
            ca = rec.compiled.cost_analysis()
            ca0 = ca[0] if isinstance(ca, (list, tuple)) else ca
            flops = ca0.get("flops")
        except Exception:  # noqa: BLE001 — backend-optional surface
            flops = None
    return {
        "collectives": dict(sorted(coll_counts.items())),
        "donated": donation.donated_labels(rec),
        # Aliased-output count from the lowered module itself: a jit site
        # that silently drops donate_argnums changes this even when the
        # registered intent above stays the same.
        "aliased": rec.stablehlo.count("tf.aliasing_output"),
        "callbacks": callbacks,
        "flops": _sig4(flops),
        "bytes": _sig4(peak_bytes),
    }


def load(path: str) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def save(path: str, fps: dict) -> None:
    with open(path, "w") as f:
        json.dump(dict(sorted(fps.items())), f, indent=1, sort_keys=True)
        f.write("\n")


def diff(fps: dict, path: str, corpus: list) -> list:
    """Current fingerprints vs the committed file -> findings. Points at
    each graph's registration site so suppressions live there."""
    committed = load(path)
    from tools.checklib import repo_root
    try:
        rel = os.path.relpath(path, repo_root())
        if rel.startswith(".."):
            rel = path
    except ValueError:
        rel = path
    sources = {rec.graph_id: rec.spec.source for rec in corpus}
    findings: list[Finding] = []
    for gid, fp in sorted(fps.items()):
        src_path, line = sources.get(gid, (rel, 0))
        if gid not in committed:
            findings.append(Finding(
                "fingerprint-missing", src_path, line,
                f"{gid}: no committed fingerprint — review and run "
                "`python -m tools.graphcheck --update-baseline`"))
            continue
        want = committed[gid]
        deltas = []
        for k in ("collectives", "donated", "aliased", "callbacks",
                  "flops", "bytes"):
            if fp.get(k) != want.get(k):
                deltas.append(f"{k} {want.get(k)!r} -> {fp.get(k)!r}")
        if deltas:
            findings.append(Finding(
                "fingerprint-drift", src_path, line,
                f"{gid}: " + "; ".join(deltas)))
    for gid in sorted(set(committed) - set(fps)):
        findings.append(Finding(
            "fingerprint-stale", rel, 0,
            f"{gid}: committed fingerprint but the graph no longer "
            "registers — `--update-baseline` after review"))
    return findings
