"""Finding class 5 — peak-memory estimate gated per graph.

`compiled.memory_analysis()` (CompiledMemoryStats, where the backend
provides it) gives argument + output + temp sizes for the compiled
module; their sum is the static peak-HBM estimate for one execution —
donation shows up here directly (a donated input's buffer is aliased
into an output instead of counted twice via temp). Graphs registered
with a `budget_bytes` fail when the estimate exceeds it; every graph
carries the estimate in its fingerprint so an unbudgeted regression is
still drift.

The estimate is CPU-lowered, so absolute numbers differ from real TPU
HBM (no rematerialization tuning, different layout padding) — budgets
gate the ORDER of the footprint, not the exact byte.
"""

from __future__ import annotations

from tools.checklib import Finding
from tools.graphcheck.lowering import LoweredGraph


def analyze(rec: LoweredGraph) -> tuple:
    """-> (peak-bytes estimate or None, findings)."""
    if rec.compiled is None:
        return None, []
    try:
        ma = rec.compiled.memory_analysis()
        peak = int(ma.argument_size_in_bytes + ma.output_size_in_bytes
                   + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
    except Exception:  # noqa: BLE001 — backend-optional surface
        return None, []
    findings: list[Finding] = []
    spec = rec.spec
    if spec.budget_bytes is not None and peak > spec.budget_bytes:
        path, line = spec.source
        findings.append(Finding(
            "hbm-over-budget", path, line,
            f"{rec.graph_id}: peak-memory estimate {peak} bytes exceeds "
            f"the registered budget {spec.budget_bytes} (args+outputs+"
            "temps-aliased)"))
    return peak, findings
