"""graphcheck: static analysis over the LOWERED XLA graphs of every
registered TPU hot path.

`tools.staticcheck` gates the *source* of the distributed plane; the TPU
hot path's worst regressions live one layer down, in the lowered graph —
a dropped `donate_argnums` silently doubles HBM, a stray `pure_callback`
inserts a device→host sync into a jitted region, a sharding edit turns an
FSDP param into an implicit full all-gather. None of that is visible to
source lints or CPU-only benchmarks. graphcheck AOT-lowers every
registered hot graph on CPU under simulated meshes (`jax.jit(...).lower()`
— no execution, no TPU) and analyzes the jaxpr + StableHLO + compiled
HLO for five finding classes:

  donation      large state-threading buffers accepted by value but not
                donated; donations XLA silently rejected
  host-sync     pure_callback / io_callback / debug_print inside graphs
                registered as steady-state hot, plus an AST companion
                flagging python-scalar coercions on traced values
  recompile     weak-typed inputs that fork the executable cache; jit
                wrappers constructed per call / per loop iteration;
                unstable static args at jit call sites
  collectives   all-gather/all-reduce/reduce-scatter/all-to-all/
                collective-permute counts per graph; lowered in-shardings
                cross-checked against the declared parallel/sharding.py
                specs; FSDP params that lower fully replicated
  memory        peak-HBM estimate from compiled.memory_analysis() gated
                against per-graph budgets

Each graph registers through a `__graphcheck__(gc)` hook in its OWN
module (train/step.py, llm/engine.py, rllib learner, channel.py) —
product code never imports tools/. Per-graph fingerprints (collective
counts by type, donated-arg set, callback count, flops/bytes) are
committed in tools/graphcheck/fingerprints.json: ANY drift fails tier-1
without running a benchmark. Findings diff against
tools/graphcheck/baseline.json with the same multiset /
`--update-baseline` / inline-`# graphcheck: ok <rule>` semantics as
staticcheck (shared impl: tools.checklib).

Run as `python -m tools.graphcheck`, through the tier-1 test
(tests/test_graphcheck.py), or as part of the unified gate
`python -m tools.staticcheck --all`.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Any, Callable

from tools.checklib import Finding, repo_root, suppressed  # noqa: F401

BASELINE_REL = "tools/graphcheck/baseline.json"
FINGERPRINTS_REL = "tools/graphcheck/fingerprints.json"

# Product modules that define a `__graphcheck__(gc)` registration hook.
# Their sources are also the corpus for the AST companion passes
# (host-sync coercions, recompile hazards at jit call sites).
HOOK_MODULES = (
    "ray_tpu.train.step",
    "ray_tpu.llm.engine",
    "ray_tpu.rllib.core.learner",
    "ray_tpu.experimental.channel",
    "ray_tpu.parallel.sharding",
)


@dataclasses.dataclass
class GraphSpec:
    """One registered hot graph under one mesh.

    `fn` is the UNJITTED python callable with every static already bound
    (functools.partial); `args` are ShapeDtypeStructs (or arrays) for the
    dynamic arguments only — lowering never executes the graph.
    """

    name: str
    fn: Callable
    args: tuple
    donate_argnums: tuple = ()
    in_shardings: Any = None       # pytree over args (NamedShardings)
    out_shardings: Any = None
    # The PRODUCTION jit wrapper, when the product module builds its own
    # (e.g. train/step.py compile_for): lowering uses it verbatim, so an
    # edit that drops donation/shardings from the product jit site is
    # analyzed as shipped, not as re-declared here. `donate_argnums`
    # stays the DECLARED intent — donation.py cross-checks it against
    # the aliasing the wrapper actually lowered.
    jit_fn: Any = None
    # Declared partition specs: tuple of (label-substring, PartitionSpec)
    # pairs cross-checked against the shardings the graph actually
    # lowered with (every flattened input arg whose label contains the
    # substring must match). The declaration should come from
    # parallel/sharding.py (declared_param_specs) so an edit that drops
    # in_shardings from the jit site diverges from the declared table
    # and fails the gate.
    declared_in_specs: tuple = ()
    hot: bool = True               # steady-state hot: host callbacks banned
    min_donate_bytes: int = 1 << 16
    # Substrings of flattened-arg labels that must NOT lower fully
    # replicated on a multi-device mesh (the FSDP-param drift gate).
    expect_sharded: tuple = ()
    budget_bytes: int | None = None
    arg_names: tuple | None = None  # labels for args; default arg0..N
    # Filled by the registry:
    mesh: Any = None
    mesh_axes: dict | None = None
    source: tuple = ("", 0)        # (repo-relative path, line) of register()


@dataclasses.dataclass
class _Registration:
    name: str
    build: Callable                # build(mesh) -> GraphSpec
    meshes: tuple                  # tuple of {axis: size} dicts (or None)
    source: tuple


_REGISTRY: dict[str, _Registration] = {}


def register(name: str, build: Callable, meshes: tuple = (None,),
             _source: tuple | None = None) -> None:
    """Called from a product module's `__graphcheck__(gc)` hook.

    `build(mesh)` returns the GraphSpec for one mesh (mesh is None for
    single-device). `meshes` is a tuple of {axis: size} dicts; None means
    the default single-device lowering. Suppressions are inline comments
    (`# graphcheck: ok <rule> — reason`) at the register() call site.
    """
    if _source is None:
        f = sys._getframe(1)
        path = os.path.abspath(f.f_code.co_filename)
        try:
            path = os.path.relpath(path, repo_root())
        except ValueError:  # other drive (windows); keep absolute
            pass
        _source = (path, f.f_lineno)
    _REGISTRY[name] = _Registration(name, build, tuple(meshes), _source)


def clear_registry() -> None:
    _REGISTRY.clear()


def load_corpus(modules: tuple = HOOK_MODULES) -> dict:
    """Import every hook module and run its `__graphcheck__(gc)` hook
    against this module. Returns the registry (name -> _Registration).
    A hook module without the hook is drift — registered in PR 10's
    contract — and raises."""
    import importlib
    gc_mod = sys.modules[__name__]
    clear_registry()
    for modname in modules:
        mod = importlib.import_module(modname)
        hook = getattr(mod, "__graphcheck__", None)
        if hook is None:
            raise RuntimeError(
                f"{modname} lost its __graphcheck__ hook (graphcheck "
                "registration drift)")
        hook(gc_mod)
    return dict(_REGISTRY)


def mesh_key(axes: dict | None) -> str:
    """Size-1 axes exist only to satisfy PartitionSpecs (the repo's
    standard mesh carries all six names); the key names the real shape."""
    if not axes:
        return "1dev"
    parts = [f"{k}{v}" for k, v in axes.items() if v > 1]
    return "_".join(parts) or "1dev"


def _spec_suppressed(root: str, spec: GraphSpec, rule: str) -> bool:
    path, line = spec.source
    full = path if os.path.isabs(path) else os.path.join(root, path)
    try:
        with open(full) as f:
            lines = f.read().splitlines()
    except OSError:
        return False
    return suppressed(lines, line, rule, tool="graphcheck")


def run(root: str | None = None, *, registry: dict | None = None,
        source_rels: tuple | None = None,
        fingerprints_path: str | None = None,
        corpus: list | None = None) -> list:
    """Lower + analyze every registered graph and scan the hook-module
    sources; returns raw findings (baseline not applied). `corpus` lets
    tests inject pre-lowered records (lower once, analyze many)."""
    from tools.graphcheck import (collectives, donation, fingerprint,
                                  hostsync, lowering, memory, recompile)
    root = root or repo_root()
    if corpus is None:
        if registry is None:
            registry = load_corpus()
        corpus = lowering.lower_all(registry)
    findings: list[Finding] = []
    fps: dict[str, dict] = {}
    for rec in corpus:
        per_graph: list[Finding] = []
        per_graph += donation.analyze(rec)
        cb_count, hs = hostsync.analyze(rec)
        per_graph += hs
        per_graph += recompile.analyze(rec)
        coll_counts, cf = collectives.analyze(rec)
        per_graph += cf
        peak, mf = memory.analyze(rec)
        per_graph += mf
        fps[rec.graph_id] = fingerprint.build(rec, cb_count, coll_counts,
                                              peak)
        findings += [f for f in per_graph
                     if not _spec_suppressed(root, rec.spec, f.rule)]
    fpath = fingerprints_path or os.path.join(root, FINGERPRINTS_REL)
    findings += fingerprint.diff(fps, fpath, corpus)
    if source_rels is None:
        source_rels = tuple(
            m.replace(".", "/") + ".py" for m in HOOK_MODULES)
    findings += hostsync.scan_sources(root, source_rels)
    findings += recompile.scan_sources(root, source_rels)
    return findings


def current_fingerprints(corpus: list) -> dict:
    """Fingerprints for an already-lowered corpus (used by
    --update-baseline to rewrite fingerprints.json)."""
    from tools.graphcheck import (collectives, fingerprint, hostsync,
                                  memory)
    fps = {}
    for rec in corpus:
        cb, _ = hostsync.analyze(rec)
        coll, _ = collectives.analyze(rec)
        peak, _ = memory.analyze(rec)
        fps[rec.graph_id] = fingerprint.build(rec, cb, coll, peak)
    return fps
