"""AOT lowering of registered graphs under simulated meshes.

No execution, no TPU: `jax.jit(fn, ...).lower(*avals)` traces and lowers
on CPU (the jax-0.4.37 seam — `.lower()` on the jit wrapper, StableHLO
via `.as_text()`), `.compile()` runs the XLA pipeline far enough to
expose the partitioned module (collectives, input shardings, memory and
cost analyses) without ever dispatching. Meshes are carved out of the
virtual CPU device set (`--xla_force_host_platform_device_count`), the
same simulation dryrun_multichip uses.
"""

from __future__ import annotations

import dataclasses
import re
import warnings
from typing import Any

import numpy as np

from tools.graphcheck import GraphSpec, mesh_key

_DONATION_REJECT = re.compile(
    r"donated buffers (?:were|was) not usable|buffer donation", re.I)


@dataclasses.dataclass
class FlatArg:
    label: str          # e.g. "state.params['layers']['wq']"
    aval: Any           # shape/dtype carrier
    arg_idx: int        # which top-level argument it flattened out of
    donated: bool


@dataclasses.dataclass
class LoweredGraph:
    spec: GraphSpec
    graph_id: str
    jaxpr: Any
    stablehlo: str
    compiled: Any            # None when compile itself failed
    hlo: str
    flat_in: list            # [FlatArg]
    flat_out_avals: list
    input_shardings: list | None
    donation_warnings: list
    error: str | None = None


def make_mesh(axes: dict | None):
    import jax
    from jax.sharding import Mesh
    if not axes:
        return None
    n = int(np.prod(list(axes.values())))
    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(
            f"mesh {axes} needs {n} devices, have {len(devs)} — run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    return Mesh(np.array(devs[:n]).reshape(*axes.values()),
                tuple(axes.keys()))


def _label_args(spec: GraphSpec) -> list:
    import jax
    names = spec.arg_names or tuple(
        f"arg{i}" for i in range(len(spec.args)))
    flat: list[FlatArg] = []
    for i, arg in enumerate(spec.args):
        leaves = jax.tree_util.tree_flatten_with_path(arg)[0]
        for path, leaf in leaves:
            label = names[i] + jax.tree_util.keystr(path)
            flat.append(FlatArg(label, leaf, i,
                                i in spec.donate_argnums))
    return flat


def lower_graph(spec: GraphSpec) -> LoweredGraph:
    import jax
    graph_id = f"{spec.name}@{mesh_key(spec.mesh_axes)}"
    jit_kwargs: dict = {}
    if spec.donate_argnums:
        jit_kwargs["donate_argnums"] = spec.donate_argnums
    if spec.in_shardings is not None:
        jit_kwargs["in_shardings"] = spec.in_shardings
    if spec.out_shardings is not None:
        jit_kwargs["out_shardings"] = spec.out_shardings

    jaxpr = jax.make_jaxpr(spec.fn)(*spec.args)
    donation_warnings: list[str] = []
    compiled = None
    hlo = ""
    input_shardings = None
    error = None
    jit_fn = spec.jit_fn if spec.jit_fn is not None else jax.jit(
        spec.fn, **jit_kwargs)
    # Fingerprints measure a FRESH compile: executables loaded from the
    # persistent compilation cache report different memory/cost estimates
    # than a cold XLA run, which would drift `bytes`/`flops` depending on
    # cache warmth (and graphcheck's own compiles would pollute the cache
    # the test suite shares). Hermetic: cache off for the compile, restored
    # after.
    cache_was = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    # The flag alone is not enough: compilation_cache.is_cache_used()
    # latches its answer on the FIRST jitted computation in the process
    # (jax 0.4.37 `_cache_checked`), so if anything jax ran before
    # graphcheck in this process with the cache on, compiles here still
    # read warm entries and report cache-loaded memory estimates.
    # reset_cache() drops the latch so the disable takes effect; a second
    # reset in the finally re-latches with the restored flag.
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — private seam, best-effort
        _cc = None
    try:
        with warnings.catch_warnings(record=True) as wlog:
            warnings.simplefilter("always")
            lowered = jit_fn.lower(*spec.args)
            stablehlo = lowered.as_text()
            try:
                compiled = lowered.compile()
                hlo = compiled.as_text()
                try:
                    input_shardings = list(compiled.input_shardings[0])
                except Exception:  # noqa: BLE001 — backend-optional surface
                    input_shardings = None
            except Exception as e:  # noqa: BLE001 — surfaced as a finding
                error = f"{type(e).__name__}: {e}"
    finally:
        jax.config.update("jax_enable_compilation_cache", cache_was)
        if _cc is not None:
            try:
                _cc.reset_cache()
            except Exception:  # noqa: BLE001 — private seam, best-effort
                pass
    for w in wlog:
        msg = str(w.message)
        if _DONATION_REJECT.search(msg):
            donation_warnings.append(msg.splitlines()[0])

    flat_out = [v.aval for v in jaxpr.jaxpr.outvars]
    return LoweredGraph(
        spec=spec, graph_id=graph_id, jaxpr=jaxpr, stablehlo=stablehlo,
        compiled=compiled, hlo=hlo, flat_in=_label_args(spec),
        flat_out_avals=flat_out, input_shardings=input_shardings,
        donation_warnings=donation_warnings, error=error)


def lower_all(registry: dict) -> list:
    """Expand every registration across its meshes and lower each."""
    corpus: list[LoweredGraph] = []
    for reg in registry.values():
        for axes in reg.meshes:
            mesh = make_mesh(axes)
            spec = reg.build(mesh)
            spec.mesh = mesh
            spec.mesh_axes = axes
            spec.source = reg.source
            corpus.append(lower_graph(spec))
    return corpus
