"""CLI: `python -m tools.racecheck [--passes escape,interleave]`.

Exit codes: 0 clean, 1 new static findings OR any interleaving
violation, 2 usage error. The static (escape) findings diff against
tools/racecheck/baseline.json; interleaving violations are hard
failures with no baseline. `RAYTPU_RACECHECK_BUDGET_S` (default 20)
bounds the exploration wall clock; `--budget` overrides.
"""

from __future__ import annotations

import argparse
import os
import sys

from tools import checklib
from tools.racecheck import (BASELINE_REL, budget_s, explore_models,
                             repo_root, run)

PASSES = ("escape", "interleave")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.racecheck",
        description="racecheck: thread-escape static analysis + "
                    "deterministic interleaving model checking")
    p.add_argument("--passes", default=",".join(PASSES),
                   help=f"comma list of {', '.join(PASSES)}")
    p.add_argument("--root", default=repo_root())
    p.add_argument("--baseline", default=None)
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept current ESCAPE findings as the baseline "
                        "(interleaving violations are never baselined)")
    p.add_argument("--files", default=None,
                   help="comma list of python files: restrict the escape "
                        "pass to exactly these (fixture/debug mode)")
    p.add_argument("--budget", type=float, default=None,
                   help="exploration wall budget in seconds (default "
                        "RAYTPU_RACECHECK_BUDGET_S or 20)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--models", default=None,
                   help="comma list restricting the interleave pass to "
                        "these protocol models")
    args = p.parse_args(argv)

    passes = tuple(s for s in args.passes.split(",") if s)
    for s in passes:
        if s not in PASSES:
            print(f"unknown pass {s!r} (have: {', '.join(PASSES)})",
                  file=sys.stderr)
            return 2

    rc = 0
    if "escape" in passes:
        targets = None
        if args.files:
            targets = tuple(
                os.path.relpath(os.path.abspath(f), args.root)
                for f in args.files.split(","))
        findings = run(args.root, targets=targets)
        bpath = args.baseline or os.path.join(args.root, BASELINE_REL)
        rc = checklib.report(findings, bpath,
                            update=args.update_baseline,
                            use_baseline=not args.no_baseline)
        if args.update_baseline:
            return rc
    if "interleave" in passes:
        budget = args.budget if args.budget is not None else budget_s()
        names = (tuple(args.models.split(",")) if args.models else None)
        violations = explore_models(budget, seed=args.seed, names=names)
        for f in violations:
            print(f.render())
        print(f"interleave: {len(violations)} violation(s) within "
              f"{budget:.0f}s budget", file=sys.stderr)
        rc = max(rc, 1 if violations else 0)
    return rc


if __name__ == "__main__":
    sys.exit(main())
