"""Thread-escape static analysis — staticcheck pass 5, the static half
of racecheck.

Builds a corpus-wide THREAD-ROLE REGISTRY from spawn sites in the
lock-heavy planes (`threading.Thread(target=self._loop)`, executor-shard
`.submit(self._fn)`, nested-def spawns `Thread(target=run)`), computes
each role's reachable method set by transitive `self.method()` closure
inside the class, and records every `self.field` READ and WRITE together
with the held-lock stack at the access (the same lock-identity machinery
as staticcheck's concurrency pass). A field is a THREAD ESCAPE when one
role WRITES it and a different role touches it with NO COMMON HELD LOCK —
the static shape of every cross-thread lost-update / torn-check bug the
chaos storms have caught dynamically.

Noise model (what deliberately does NOT fire):

  - writes inside `__init__` (and methods reachable only from it):
    construction happens-before every spawn, so boot-time publication is
    ordered;
  - fields whose only post-boot writes are ONE constant value (monotonic
    latches: `self._shutdown = True` read by loops — the CPython
    GIL-published flag idiom this codebase uses deliberately);
  - lock-like attributes themselves (`self.lock`, `self._cv`, ...);
  - container METHOD mutation (`self.q.append(x)`, `self.d[k] = v`):
    single bytecode container ops are GIL-atomic; this pass targets
    attribute REBINDING and read-modify-write (`self.x = ...`,
    `self.x += 1`), where interleaving loses updates even under the GIL.

Roles: every spawn target (plus everything it reaches) is one role; all
methods not reachable from any spawn site form the single `api` role
(external callers — client threads, the listener's dispatch, etc.).
A method reachable from several spawn sites belongs to each of them.

Findings carry rule `thread-escape`, diff against an EMPTY baseline on
core, and suppress inline with `# racecheck: ok thread-escape <reason>`
(checked at BOTH access sites of a pair, so the justification can live at
whichever side states the design — e.g. a seqlock field or an atomics-
style counter read torn by design).
"""

from __future__ import annotations

import ast
import os

from tools.checklib import Finding, suppressed
from tools.staticcheck import concurrency as conc

# Same lock-heavy corpus as the concurrency pass: the planes whose spawn
# sites are the listener / ingest / health / dial / copier / reply-batcher
# / executor-shard threads the module docstring names.
TARGETS = conc.TARGETS

_SAFE_CTORS = {
    # assignments of these never make the FIELD unsafe to touch (the
    # object's own thread-safety is its contract); rebinding still counts.
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Queue", "deque", "ThreadPoolExecutor",
}


def _target_qualname(call: ast.Call) -> ast.AST | None:
    """The spawn target expression of a Thread(...) / .submit(...) call,
    or None when this call spawns nothing."""
    f = call.func
    fname = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    if fname == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
        return None
    if fname == "submit" and call.args:
        return call.args[0]
    return None


def _spawn_target_name(expr) -> tuple[str, str] | None:
    """-> ("self", method) | ("local", name) for resolvable targets."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return ("self", expr.attr)
    if isinstance(expr, ast.Name):
        return ("local", expr.id)
    if isinstance(expr, ast.Call):  # functools.partial(self._fn, ...)
        fn = expr.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if name == "partial" and expr.args:
            return _spawn_target_name(expr.args[0])
    return None


class _Access:
    __slots__ = ("kind", "locks", "line", "qual", "roles", "variants")

    def __init__(self, kind, locks, line, qual):
        self.kind = kind          # "read" | "write"
        self.locks = locks        # frozenset of lock identities (local)
        self.line = line
        self.qual = qual          # "Class.method"
        self.roles = set()        # filled by role attribution
        self.variants = [locks]   # lock sets incl. caller contexts


class _AccessWalker:
    """Held-lock-tracking walk of one function body collecting self.field
    accesses and self-call edges (for role reachability)."""

    def __init__(self, corpus, module, cname):
        self.corpus = corpus
        self.module = module
        self.cname = cname
        self.held: list = []
        self.accesses: dict[str, list[_Access]] = {}
        # self.method name -> set of frozenset(lock ids) held at callsite
        self.calls: dict[str, set] = {}
        self.local_calls: set[str] = set()  # nested-def names called
        self.const_writes: dict[str, set] = {}  # attr -> literal reprs
        self.nonconst_write: set[str] = set()

    def walk(self, fn, qual):
        self.qual = qual
        for stmt in fn.body:
            self._stmt(stmt)

    def _stmt(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs walk as their own role roots
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in node.items:
                self._expr(item.context_expr)
                lk = conc._lock_of_expr(item.context_expr, self.corpus,
                                        self.cname)
                if lk is not None:
                    self.held.append(lk)
                    pushed += 1
            for s in node.body:
                self._stmt(s)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(node, ast.Assign):
            self._expr(node.value)
            for t in node.targets:
                self._target(t, node.value)
            return
        if isinstance(node, ast.AugAssign):
            self._expr(node.value)
            self._target(node.target, None, aug=True)
            return
        if isinstance(node, (ast.AnnAssign,)) and node.value is not None:
            self._expr(node.value)
            self._target(node.target, node.value)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)
            elif isinstance(child, ast.excepthandler):
                for s in child.body:
                    self._stmt(s)

    def _is_self_attr(self, node) -> bool:
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")

    def _target(self, t, value, aug: bool = False):
        if self._is_self_attr(t):
            self._record(t.attr, "write", t.lineno)
            if aug:
                # read-modify-write: the load half races too
                self._record(t.attr, "read", t.lineno)
                self.nonconst_write.add(t.attr)
            else:
                self._note_write_value(t.attr, value)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._target(el, None)
            if value is not None:
                pass  # value already visited
        else:
            # Subscript/attribute-chain stores: container mutation — the
            # documented GIL-atomic carve-out. Still visit the receiver
            # as a READ of the outer field.
            self._expr(t)

    def _note_write_value(self, attr, value):
        if isinstance(value, ast.Constant) \
                and isinstance(value.value, (bool, int, float, str,
                                             type(None))):
            self.const_writes.setdefault(attr, set()).add(
                repr(value.value))
        elif isinstance(value, ast.Call) and (
                (value.func.attr if isinstance(value.func, ast.Attribute)
                 else getattr(value.func, "id", "")) in _SAFE_CTORS):
            self.const_writes.setdefault(attr, set()).add("<safe-ctor>")
        else:
            self.nonconst_write.add(attr)

    def _expr(self, node):
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self":
                    self.calls.setdefault(f.attr, set()).add(
                        frozenset(h.identity for h in self.held))
                elif isinstance(f, ast.Name):
                    self.local_calls.add(f.id)
                tgt = _target_qualname(n)
                if tgt is not None:
                    continue
            if self._is_self_attr(n) and isinstance(n.ctx, ast.Load):
                # `self.x.append(...)` / `self.x[k]` read the binding;
                # `self.x` as a call receiver likewise.
                self._record(n.attr, "read", n.lineno)

    def _record(self, attr, kind, line):
        if conc._lock_like(attr) or attr.startswith("__"):
            return
        locks = frozenset(h.identity for h in self.held)
        self.accesses.setdefault(attr, []).append(
            _Access(kind, locks, line, self.qual))


class _ClassModel:
    def __init__(self, module, cname, methods):
        self.module = module
        self.cname = cname
        self.methods = methods          # name -> FunctionDef
        self.nested: dict[str, dict] = {}  # method -> {name: FunctionDef}
        for mname, fn in methods.items():
            self.nested[mname] = {
                n.name: n for n in ast.walk(fn)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn}

    def spawn_roles(self) -> dict[str, dict]:
        """role name -> {"fns": [root FunctionDef], "sites":
        [(spawning method, line)]} — the sites carry the fork
        happens-before edge (writes above a spawn in the spawning method
        are ordered before everything the spawned role does)."""
        roles: dict[str, dict] = {}
        for mname, fn in self.methods.items():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                tgt = _target_qualname(node)
                if tgt is None:
                    continue
                resolved = _spawn_target_name(tgt)
                if resolved is None:
                    continue
                kind, name = resolved
                if kind == "self" and name in self.methods:
                    ent = roles.setdefault(name,
                                           {"fns": [], "sites": []})
                    ent["fns"].append(self.methods[name])
                elif kind == "local" and name in self.nested.get(mname, {}):
                    ent = roles.setdefault(f"{mname}.<{name}>",
                                           {"fns": [], "sites": []})
                    ent["fns"].append(self.nested[mname][name])
                else:
                    continue
                ent["sites"].append((mname, node.lineno))
        return roles


def _walk_fn(corpus, module, cname, fn, qual) -> _AccessWalker:
    w = _AccessWalker(corpus, module, cname)
    w.walk(fn, qual)
    return w


def _closure(model: _ClassModel, walks: dict, roots: list) -> set:
    """Method names reachable from `roots` via self-calls (transitive)."""
    seen: set[str] = set()
    frontier = list(roots)
    while frontier:
        name = frontier.pop()
        if name in seen or name not in model.methods:
            continue
        seen.add(name)
        frontier.extend(walks[name].calls)
    return seen


def _externally_called(modules) -> set:
    """Method names invoked on a NON-self receiver anywhere in the corpus
    (`self.runtime._on_x()`, `rt.submit()`, `w.drain()`): these are entry
    points some OTHER module's thread can drive, so they root the
    external role even when private."""
    out: set = set()
    for m in modules:
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                recv = node.func.value
                if not (isinstance(recv, ast.Name) and recv.id == "self"):
                    out.add(node.func.attr)
    return out


def run(root: str, targets: tuple | None = None) -> list[Finding]:
    rels = [t for t in (targets or TARGETS)
            if os.path.exists(os.path.join(root, t))]
    modules = [conc._Module(root, rel) for rel in rels]
    corpus = conc._Corpus(modules)
    external = _externally_called(modules)
    findings: list[Finding] = []
    for m in modules:
        for cname, methods in m.classes.items():
            findings.extend(_check_class(corpus, m, cname, methods,
                                         external))
    return findings


_MAX_CONTEXTS = 6


def _check_class(corpus, module, cname, methods,
                 external: set) -> list[Finding]:
    model = _ClassModel(module, cname, methods)
    roles = model.spawn_roles()
    if not roles:
        return []

    # Walk every method once; nested role roots walk separately.
    walks: dict[str, _AccessWalker] = {}
    for mname, fn in methods.items():
        walks[mname] = _walk_fn(corpus, module, cname, fn,
                                f"{cname}.{mname}")
    role_reach: dict[str, set] = {}
    role_extra_walks: dict[str, _AccessWalker] = {}
    fork_hb: dict[str, dict] = {}   # role -> {spawning method: min line}
    for rname, ent in roles.items():
        if "." in rname:  # nested-def role: walk the nested body itself
            w = _walk_fn(corpus, module, cname, ent["fns"][0],
                         f"{cname}.{rname}")
            role_extra_walks[rname] = w
            role_reach[rname] = _closure(model, walks, list(w.calls))
        else:
            role_reach[rname] = _closure(model, walks, [rname])
        hb: dict[str, int] = {}
        for mname, line in ent["sites"]:
            hb[mname] = min(line, hb.get(mname, line))
        fork_hb[rname] = hb

    # Boot-only methods: reachable from __init__ and from nowhere else —
    # they run before any spawn, so their writes are ordered (fixpoint:
    # a method stays boot-only while every caller is __init__/boot-only).
    boot_reach = _closure(model, walks, ["__init__"]) \
        if "__init__" in methods else set()
    callers: dict[str, set] = {}
    for mn, w in walks.items():
        for callee in w.calls:
            callers.setdefault(callee, set()).add(mn)
    boot_only = set(boot_reach)
    changed = True
    while changed:
        changed = False
        for mn in list(boot_only):
            outside = {c for c in callers.get(mn, ())
                       if c != "__init__" and c not in boot_only}
            if outside:
                boot_only.discard(mn)
                changed = True
    # The external role roots: methods some OTHER thread can enter
    # directly — public surface, corpus-wide non-self callees, or in-class
    # orphans (no in-class caller). A private helper only ever reached
    # from a thread loop stays in that loop's role alone.
    api_roots = [mn for mn in methods
                 if mn != "__init__" and mn not in boot_only
                 and mn not in roles
                 and (not mn.startswith("_") or mn in external
                      or mn not in callers)]
    role_reach["api"] = _closure(model, walks, api_roots)

    # ---- caller-held-lock context propagation ----
    # CONTEXTS(m): the lock sets m can be ENTERED under. Role/api roots
    # enter lock-free; each in-class callsite contributes (caller ctx |
    # site locks). Fixpoint; above the cap a method's contexts collapse
    # to their intersection (the locks guaranteed on every path).
    contexts: dict[str, set] = {mn: set() for mn in methods}
    entry = set(api_roots) | {rn for rn in roles if "." not in rn}
    for mn in entry:
        contexts[mn].add(frozenset())
    work = list(entry)
    nested_ctx = frozenset()
    for rname, w in role_extra_walks.items():
        # nested-def role bodies enter lock-free; seed their callees
        for callee, sites in w.calls.items():
            if callee in contexts:
                for site_locks in sites:
                    if (nested_ctx | site_locks) not in contexts[callee]:
                        contexts[callee].add(nested_ctx | site_locks)
                        work.append(callee)
    while work:
        mn = work.pop()
        w = walks.get(mn)
        if w is None:
            continue
        for callee, sites in w.calls.items():
            if callee not in contexts:
                continue
            tgt = contexts[callee]
            before = len(tgt)
            for ctx in list(contexts[mn]) or [frozenset()]:
                for site_locks in sites:
                    tgt.add(ctx | site_locks)
            if len(tgt) > _MAX_CONTEXTS:
                common = frozenset.intersection(*tgt)
                tgt.clear()
                tgt.add(common)
            if len(tgt) != before:
                work.append(callee)

    def variants(mname: str, local: frozenset) -> list:
        ctxs = contexts.get(mname) or {frozenset()}
        return [c | local for c in ctxs]

    # ---- aggregate accesses per field per role ----
    per_field: dict[str, list[_Access]] = {}
    const_vals: dict[str, set] = {}
    nonconst: set = set()

    def absorb(w: _AccessWalker, rnames: list, mname: str | None):
        for attr, accs in w.accesses.items():
            for a in accs:
                a2 = _Access(a.kind, a.locks, a.line, a.qual)
                a2.roles = set(rnames)
                a2.variants = (variants(mname, a.locks) if mname
                               else [a.locks])
                per_field.setdefault(attr, []).append(a2)
        for attr, vals in w.const_writes.items():
            const_vals.setdefault(attr, set()).update(vals)
        nonconst.update(w.nonconst_write)

    for mname, w in walks.items():
        rnames = [rn for rn, reach in role_reach.items() if mname in reach]
        if not rnames:
            continue  # boot-only method
        absorb(w, rnames, mname)
    for rname, w in role_extra_walks.items():
        absorb(w, [rname], None)

    # ---- the escape rule ----
    findings: list[Finding] = []
    lines = module.lines
    for attr, accs in sorted(per_field.items()):
        writes = [a for a in accs if a.kind == "write"]
        if not writes:
            continue
        # Monotonic-latch / safe-ctor carve-out: every post-boot write is
        # one constant (or a thread-safe ctor) => publication-only field.
        if attr not in nonconst and len(const_vals.get(attr, ())) <= 1:
            continue
        hit = _first_unlocked_pair(writes, accs, cname, fork_hb)
        if hit is None:
            continue
        w, other = hit
        if suppressed(lines, w.line, "thread-escape", tool="racecheck") \
                or suppressed(lines, other.line, "thread-escape",
                              tool="racecheck"):
            continue
        wl = ",".join(sorted(w.locks)) or "no lock"
        ol = ",".join(sorted(other.locks)) or "no lock"
        findings.append(Finding(
            "thread-escape", module.rel, w.line,
            f"{cname}.{attr}: written in {w.qual} "
            f"[{_rolestr(w)}] under {wl}; {other.kind} in {other.qual} "
            f"[{_rolestr(other)}] under {ol} — no common lock",
        ))
    return findings


def _rolestr(a: _Access) -> str:
    return "+".join(sorted(a.roles))


def _fork_ordered(x: _Access, y: _Access, cname: str,
                  fork_hb: dict) -> bool:
    """True when `x` is in the spawning method ABOVE the spawn site of
    the sole role `y` runs in — the fork happens-before edge (configure
    state, then start the thread)."""
    if len(y.roles) != 1:
        return False
    hb = fork_hb.get(next(iter(y.roles)))
    if not hb:
        return False
    meth = x.qual.removeprefix(cname + ".")
    return meth in hb and x.line < hb[meth]


def _first_unlocked_pair(writes, accs, cname, fork_hb):
    """First (write, access) pair that can run on different threads with
    provably disjoint lock sets on SOME pair of entry contexts."""
    for w in writes:
        for a in accs:
            if a is w:
                continue
            if a.roles == w.roles and len(w.roles) == 1:
                continue  # one role on both sides: same thread
            if _fork_ordered(w, a, cname, fork_hb) \
                    or _fork_ordered(a, w, cname, fork_hb):
                continue  # ordered by Thread.start()
            if not any(not (vw & va)
                       for vw in w.variants for va in a.variants):
                continue  # every context pair shares a lock
            return (w, a)
    return None
