"""racecheck: the third static-analysis plane — concurrency semantics.

The repo's static gates now cover three planes:

  tools.staticcheck   source conventions (wire drift, lock discipline,
                      no-pickle scopes, fd/thread hygiene, chaos sites)
  tools.graphcheck    lowered XLA graphs (donation, host sync, recompile,
                      collective drift, memory)
  tools.racecheck     concurrency SEMANTICS: who may touch what from
                      which thread (static thread-escape analysis), and
                      whether the distributed protocol cores hold their
                      invariants under EVERY bounded interleaving
                      (deterministic schedule exploration)

Two cooperating passes:

  escape       staticcheck pass 5: corpus-wide thread-role registry from
               spawn sites; flags fields written by one role and touched
               by another with no common held lock (`thread-escape`).
               Findings diff against tools/racecheck/baseline.json
               (ships EMPTY on core); suppress inline with
               `# racecheck: ok thread-escape <reason>`.
  interleave   CHESS/PCT-style deterministic interleaving explorer run
               over the REAL protocol cores single-process (lease
               return/spill/dedup, store reserve/publish/reclaim, the
               two-phase checkpoint commit, the stream-resume cursor),
               asserting machine-checked invariants: exactly-once
               execution per (task_id, lease_seq), no double-release of
               reservation extents, latest-committed manifest never
               regresses, delivered token positions never re-emit or
               skip. Yield points ride the chaos plane's sites
               (`chaos.set_schedule_hook`) plus cooperative locks.

Run `python -m tools.racecheck` (exit 1 on any new static finding OR any
interleaving violation), or as the third stage of
`python -m tools.staticcheck --all`. The exploration budget is bounded
and deterministic: `RAYTPU_RACECHECK_BUDGET_S` (default 20s) splits
across the registered protocol models, exhaustive-first then PCT seeds.
"""

from __future__ import annotations

import os

from tools.checklib import Finding, repo_root  # noqa: F401

BASELINE_REL = "tools/racecheck/baseline.json"
DEFAULT_BUDGET_S = 20.0


def run(root: str | None = None,
        targets: tuple | None = None) -> list[Finding]:
    """The static (thread-escape) pass; explorer violations are produced
    by explore_models() — they are hard failures, never baselined."""
    from tools.racecheck import escape
    return escape.run(root or repo_root(), targets=targets)


def budget_s() -> float:
    try:
        return float(os.environ.get("RAYTPU_RACECHECK_BUDGET_S",
                                    DEFAULT_BUDGET_S))
    except ValueError:
        return DEFAULT_BUDGET_S


def explore_models(budget: float | None = None, seed: int = 0,
                   names: tuple | None = None) -> list[Finding]:
    """Run every registered protocol model under schedule enumeration;
    each violation renders as one Finding with rule
    `interleaving-violation` (path = the module owning the core)."""
    from tools.racecheck import protocols
    return protocols.run_all(budget if budget is not None else budget_s(),
                             seed=seed, names=names)
