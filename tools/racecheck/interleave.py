"""Deterministic interleaving explorer — the dynamic half of racecheck.

CHESS/PCT-style systematic schedule exploration for the repo's
distributed-control-plane protocol cores, run SINGLE-PROCESS under a
cooperative scheduler: every logical thread is a real thread, but exactly
one holds the run token at any instant, and the token only changes hands
at YIELD POINTS. Yield points come from three places:

  - `CooperativeLock.acquire()/release()` — the harness-supplied lock the
    model swaps into the object under test (`self.lock`, `self._lease_lock`
    ...), so every critical-section boundary is a schedule point;
  - `chaos.site(...)` markers — the 26+ seeded fault sites (PR 8) already
    threaded through transport/store/agent/train/serve double as schedule
    points for free via `chaos.set_schedule_hook` (zero overhead when no
    explorer is attached, exactly like a disarmed chaos plane);
  - explicit `api.point()` calls in model/fixture code (queue/deque ops,
    protocol step boundaries).

Two enumeration strategies share one decision-trace core, so a fault
branch (`api.choice(n)` — e.g. "does the peer die here?") is explored the
same way a context switch is:

  exhaustive    DFS over the decision tree with a PREEMPTION BOUND
                (CHESS): switching away from a runnable thread costs one
                preemption; at the bound the scheduler must run the
                current thread on. Bound 2-3 covers the overwhelming
                majority of real concurrency bugs at polynomial cost.
  PCT           probabilistic concurrency testing: random per-thread
                priorities plus d-1 priority-change points, seeded, so
                each run is a deterministic schedule and a found bug
                replays from (seed, run index).

A VIOLATION is any of: an invariant check failing after the run, an
uncaught exception inside a logical thread (the PR 8 listener-kill shape:
a dying control thread IS the bug), a deadlock (every live thread blocked
on a cooperative lock), or a livelock (step budget exhausted). The first
violating schedule is returned with its full decision trace and yield-
point log, and replays deterministically: same model + same strategy
state => same interleaving.

Models build fresh state per schedule via `build(api)` and return a dict:

    {"threads": [(name, fn), ...],   # logical threads, run to completion
     "check": fn | None}             # post-run invariant assertions

This module is dependency-free (stdlib only) so fixtures under
tests/data/ can drive it without the product tree on the path.
"""

from __future__ import annotations

import random
import threading
import time


class _Abort(BaseException):
    """Internal: unwind a logical thread after the run is cancelled."""


class Violation(Exception):
    """An invariant the model checks raised (or the harness detected a
    deadlock/livelock/thread death)."""


class CooperativeLock:
    """Drop-in for threading.Lock/RLock under the cooperative scheduler.

    acquire() yields BEFORE taking the lock (the classic race window:
    check-then-act straddling the boundary), blocks cooperatively while
    another logical thread owns it, and release() yields after freeing it
    so a waiter can be scheduled immediately."""

    def __init__(self, sched: "Scheduler", reentrant: bool = False,
                 name: str = "lock"):
        self._s = sched
        self._reentrant = reentrant
        self.name = name
        self._owner = None      # logical thread or None
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        s = self._s
        me = s._current()
        if me is None:  # foreign (non-logical) thread: degrade to no-op
            return True
        s._yield_point(f"{self.name}.acquire")
        if self._owner is me:
            if self._reentrant:
                self._depth += 1
                return True
            raise Violation(
                f"relock of non-reentrant {self.name} by {me.name}")
        while self._owner is not None:
            if not blocking:
                return False
            s._block(me, self)
        self._owner = me
        self._depth = 1
        return True

    def release(self) -> None:
        me = self._s._current()
        if me is None:
            return
        if self._owner is not me:
            raise Violation(
                f"{me.name} released {self.name} it does not hold")
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
            self._s._unblock_waiters(self)
        self._s._yield_point(f"{self.name}.release")

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _LThread:
    __slots__ = ("name", "fn", "ev", "state", "real", "exc", "waiting_on",
                 "prio")

    def __init__(self, name, fn):
        self.name = name
        self.fn = fn
        self.ev = threading.Event()
        self.state = "ready"   # ready | running | blocked | done
        self.real = None
        self.exc = None
        self.waiting_on = None
        self.prio = 0.0


class Api:
    """What a model/fixture's `build(api)` sees."""

    def __init__(self, sched: "Scheduler"):
        self._s = sched

    def lock(self, reentrant: bool = False,
             name: str = "lock") -> CooperativeLock:
        return CooperativeLock(self._s, reentrant, name)

    def point(self, site: str = "point") -> None:
        """Explicit yield point (queue/deque op, protocol step edge)."""
        self._s._yield_point(site)

    def choice(self, n: int, site: str = "choice") -> int:
        """A fault/branch decision the strategies enumerate exactly like
        a context switch (exhaustive walks every arm)."""
        return self._s._choice(n, site)

    def fired(self, site: str) -> bool:
        """Sugar: a binary fault branch ('does the peer die here?')."""
        return self._s._choice(2, site) == 1

    def trace(self) -> list:
        return list(self._s.log)


# ---------------- strategies ----------------


class ExhaustiveStrategy:
    """DFS over the decision tree with a preemption bound. Decision 0 is
    always "continue the current thread" when it is runnable, so the
    first schedule is the non-preemptive one and the bound prunes only
    voluntary switches."""

    name = "exhaustive"

    def __init__(self, max_preemptions: int = 2):
        self.max_preemptions = max_preemptions
        self.prefix: list[list[int]] = []  # [chosen, n_choices]
        self.pos = 0
        self.preemptions = 0
        self.complete = False

    def begin_run(self, threads):
        self.pos = 0
        self.preemptions = 0

    def _decide(self, n: int) -> int:
        if n <= 1:
            return 0
        if self.pos < len(self.prefix):
            ent = self.prefix[self.pos]
            ent[1] = n
            idx = min(ent[0], n - 1)
        else:
            self.prefix.append([0, n])
            idx = 0
        self.pos += 1
        return idx

    def pick(self, current, runnable):
        if current is not None and current.state != "done" \
                and current.waiting_on is None:
            # current could keep running: switching away is a preemption
            if self.preemptions >= self.max_preemptions:
                return current
            others = [t for t in runnable if t is not current]
            idx = self._decide(1 + len(others))
            if idx == 0:
                return current
            self.preemptions += 1
            return others[idx - 1]
        # current blocked/done: a switch is forced, not a preemption
        idx = self._decide(len(runnable))
        return runnable[idx]

    def choice(self, n: int) -> int:
        return self._decide(n)

    def next_run(self) -> bool:
        # Drop stale tail from a longer previous run, then increment the
        # deepest decision that still has unexplored arms.
        del self.prefix[self.pos:]
        while self.prefix and self.prefix[-1][0] + 1 >= self.prefix[-1][1]:
            self.prefix.pop()
        if not self.prefix:
            self.complete = True
            return False
        self.prefix[-1][0] += 1
        return True

    def state_repr(self) -> str:
        return "exhaustive:" + ",".join(str(c) for c, _ in
                                        self.prefix[:self.pos])


class PCTStrategy:
    """Probabilistic concurrency testing (Burckhardt et al.): random
    priorities + d-1 priority-change points give a 1/(n * k^(d-1))
    detection guarantee for depth-d bugs; each seed is one deterministic
    schedule."""

    name = "pct"

    def __init__(self, seed: int, depth: int = 3, length_hint: int = 256):
        self.seed = seed
        self.rng = random.Random(seed)
        self.depth = depth
        self.length_hint = length_hint
        self.step = 0
        self.change_points: set[int] = set()
        self.complete = False

    def begin_run(self, threads):
        self.rng = random.Random(self.seed)
        self.step = 0
        for t in threads:
            t.prio = self.rng.random()
        self.change_points = {
            self.rng.randrange(1, max(2, self.length_hint))
            for _ in range(max(0, self.depth - 1))}

    def pick(self, current, runnable):
        self.step += 1
        if self.step in self.change_points and current is not None:
            current.prio = min(t.prio for t in runnable) - 1.0
        return max(runnable, key=lambda t: t.prio)

    def choice(self, n: int) -> int:
        return self.rng.randrange(n) if n > 1 else 0

    def next_run(self) -> bool:
        return False  # one seed, one schedule; the driver rotates seeds

    def state_repr(self) -> str:
        return f"pct:seed={self.seed},d={self.depth}"


# ---------------- the scheduler ----------------


class Scheduler:
    """One schedule execution: real threads, one token."""

    # Step budget: a model that exceeds this under SOME schedule is
    # livelocked (e.g. an unpaced retry loop that never cedes progress).
    MAX_STEPS = 50_000

    def __init__(self, strategy):
        self.strategy = strategy
        self.threads: list[_LThread] = []
        self.by_ident: dict[int, _LThread] = {}
        self.log: list[tuple] = []
        self.failure: str | None = None
        self.abort = False
        self.steps = 0
        self._main_ev = threading.Event()

    # -- thread identity --

    def _current(self) -> _LThread | None:
        return self.by_ident.get(threading.get_ident())

    def _runnable(self) -> list[_LThread]:
        return [t for t in self.threads if t.state == "ready"]

    # -- decision points --

    def _choice(self, n: int, site: str) -> int:
        lt = self._current()
        if self.abort:
            raise _Abort
        idx = self.strategy.choice(n)
        self.log.append((lt.name if lt else "?", f"{site}[{idx}/{n}]"))
        return idx

    def _yield_point(self, site: str) -> None:
        lt = self._current()
        if lt is None:
            return  # a non-logical thread wandered in: never gate it
        if self.abort:
            raise _Abort
        self.steps += 1
        if self.steps > self.MAX_STEPS:
            self._fail(f"livelock: schedule exceeded {self.MAX_STEPS} "
                       "yield points")
            self._abort_all()
            raise _Abort
        self.log.append((lt.name, site))
        runnable = self._runnable() + [lt]
        nxt = self.strategy.pick(lt, runnable)
        if nxt is lt:
            return
        lt.state = "ready"
        self._hand_token(nxt)
        self._wait_token(lt)

    def _block(self, lt: _LThread, lock) -> None:
        """Current thread cannot proceed until `lock` frees."""
        lt.state = "blocked"
        lt.waiting_on = lock
        runnable = self._runnable()
        if not runnable:
            self._fail(
                "deadlock: all live threads blocked on cooperative locks "
                f"({', '.join(t.name for t in self.threads if t.state == 'blocked')})")
            self._abort_all()
            raise _Abort
        nxt = self.strategy.pick(None, runnable)
        self._hand_token(nxt)
        self._wait_token(lt)

    def _unblock_waiters(self, lock) -> None:
        for t in self.threads:
            if t.state == "blocked" and t.waiting_on is lock:
                t.state = "ready"
                t.waiting_on = None

    def _hand_token(self, nxt: _LThread) -> None:
        nxt.state = "running"
        nxt.waiting_on = None
        nxt.ev.set()

    def _wait_token(self, lt: _LThread) -> None:
        lt.ev.wait()
        lt.ev.clear()
        if self.abort:
            raise _Abort
        lt.state = "running"

    def _fail(self, msg: str) -> None:
        if self.failure is None:
            self.failure = msg

    def _abort_all(self) -> None:
        self.abort = True
        for t in self.threads:
            if t.state != "done":
                t.ev.set()
        self._main_ev.set()

    # -- thread lifecycle --

    def _thread_body(self, lt: _LThread) -> None:
        lt.ev.wait()
        lt.ev.clear()
        if not self.abort:
            lt.state = "running"
            try:
                lt.fn()
            except _Abort:
                pass
            except BaseException as e:  # noqa: BLE001 — the violation class
                lt.exc = e
                self._fail(f"thread {lt.name!r} died: {type(e).__name__}: "
                           f"{e}")
                self._abort_all()
        lt.state = "done"
        self._on_thread_done(lt)

    def _on_thread_done(self, lt: _LThread) -> None:
        if self.abort:
            if all(t.state == "done" for t in self.threads):
                self._main_ev.set()
            return
        runnable = self._runnable()
        if runnable:
            nxt = self.strategy.pick(None, runnable)
            self._hand_token(nxt)
            return
        blocked = [t for t in self.threads if t.state == "blocked"]
        if blocked:
            self._fail("deadlock: "
                       + ", ".join(t.name for t in blocked)
                       + " blocked with no runnable thread left")
            self._abort_all()
            return
        self._main_ev.set()  # everything done

    # -- one schedule --

    def run(self, build) -> str | None:
        """Execute one schedule of `build(api)`; returns the violation
        message or None."""
        try:
            from ray_tpu.core import chaos
        except ImportError:  # product tree absent: explicit points only
            chaos = None
        api = Api(self)
        prog = build(api)
        check = prog.get("check")
        cleanup = prog.get("cleanup")
        for name, fn in prog["threads"]:
            lt = _LThread(name, fn)
            self.threads.append(lt)
        self.strategy.begin_run(self.threads)
        old_hook = (chaos.set_schedule_hook(self._yield_point)
                    if chaos is not None else None)
        try:
            for lt in self.threads:
                lt.real = threading.Thread(
                    target=self._thread_body, args=(lt,), daemon=True,
                    name=f"racecheck-{lt.name}")
                lt.real.start()
                self.by_ident[lt.real.ident] = lt
            first = self.strategy.pick(None, self._runnable())
            self._hand_token(first)
            if not self._main_ev.wait(timeout=60):
                self._fail("hung schedule: a logical thread blocked in a "
                           "real (non-cooperative) call")
                self._abort_all()
            for lt in self.threads:
                lt.real.join(timeout=5)
        finally:
            if chaos is not None:
                chaos.set_schedule_hook(old_hook)
        if self.failure is None and check is not None:
            try:
                check()
            except AssertionError as e:
                self._fail(f"invariant violated: {e}")
            except Violation as e:
                self._fail(str(e))
        if cleanup is not None:
            try:
                cleanup()
            except Exception:  # noqa: BLE001 — cleanup is best-effort
                pass
        return self.failure

    def render_trace(self, limit: int = 80) -> str:
        tail = self.log[-limit:]
        lines = [f"  {name} @ {site}" for name, site in tail]
        if len(self.log) > limit:
            lines.insert(0, f"  ... ({len(self.log) - limit} earlier "
                            "points elided)")
        return "\n".join(lines)


# ---------------- the exploration driver ----------------


class ExploreResult:
    def __init__(self):
        self.violation: str | None = None
        self.schedule: str | None = None   # strategy state that found it
        self.trace: str = ""
        self.schedules = 0
        self.exhaustive_complete = False

    def __repr__(self):
        s = "clean" if self.violation is None else "VIOLATION"
        return (f"<ExploreResult {s} schedules={self.schedules} "
                f"complete={self.exhaustive_complete}>")


def explore(build, *, seed: int = 0, max_preemptions: int = 2,
            max_schedules: int = 20_000, budget_s: float | None = None,
            pct_schedules: int = 128, pct_depth: int = 3) -> ExploreResult:
    """Bounded exhaustive pass first (complete for small models), then
    PCT seeds with whatever budget remains. Deterministic for a given
    (model, seed, bounds): wall-budget exhaustion can only truncate the
    tail of the search, never reorder it, so the first violation found is
    stable across runs that get at least that far."""
    deadline = None if budget_s is None else time.monotonic() + budget_s
    res = ExploreResult()

    def out_of_budget() -> bool:
        return deadline is not None and time.monotonic() >= deadline

    ex = ExhaustiveStrategy(max_preemptions=max_preemptions)
    while res.schedules < max_schedules and not out_of_budget():
        sched = Scheduler(ex)
        failure = sched.run(build)
        res.schedules += 1
        if failure is not None:
            res.violation = failure
            res.schedule = ex.state_repr()
            res.trace = sched.render_trace()
            return res
        if not ex.next_run():
            res.exhaustive_complete = True
            break
    if res.exhaustive_complete:
        return res
    # Exhaustive truncated (bound/budget/cap): sweep PCT seeds on top.
    for i in range(pct_schedules):
        if out_of_budget():
            break
        pct = PCTStrategy(seed=seed * 10_007 + i, depth=pct_depth)
        sched = Scheduler(pct)
        failure = sched.run(build)
        res.schedules += 1
        if failure is not None:
            res.violation = failure
            res.schedule = pct.state_repr()
            res.trace = sched.render_trace()
            return res
    return res
