"""The REAL protocol cores under the interleaving explorer.

Each model binds the SHIPPED methods of a distributed protocol core onto
a harness object (`types.MethodType` — the decision logic that runs in
production is byte-for-byte what the explorer schedules), swaps its locks
for CooperativeLocks, stubs only the transport/effect edges (socket
sends, remote calls), and asserts the protocol's machine-checked
invariant over every explored interleaving:

  lease_return      Runtime._on_lease_return + _on_lease_spilled +
                    _find/_pop_lease_locked: the spill-to-dead-peer race
                    (head requeue vs origin agent's lease_return
                    fallback) enqueues EXACTLY ONCE per (task_id,
                    lease_seq) and releases the reservation token
                    exactly once — the PR 2 duplicate-execution bug's
                    fixed shape.
  lease_dedup       NodeAgent._lease_dup_locked: a head re-drive racing
                    the original grant delivery queues the lease once.
  store_reserve     the real shm store's write-reservation plane
                    (SharedMemoryStore._reserved_create / seal /
                    release_reservation / reclaim_orphans on a private
                    arena): no double-release of reservation extents,
                    rsv_unused returns to zero, every sealed object
                    readable — under concurrent writers, mid-flight
                    releases and liveness sweeps.
  ckpt_two_phase    train/checkpoint.py's atomic layout + the REAL
                    TorchTrainer._commit_if_ready: the latest committed
                    manifest never regresses and a torn directory is
                    never resumable, across rank deaths before ack,
                    manifest loss, and controller raise — the PR 9
                    lost-commit bug's fixed shape.
  stream_resume     llm/serve.py's _DisaggServerImpl admission +
                    _stream_tokens recovery cursor (real _admit /
                    _release / _run_admitted / _stream_tokens): token
                    positions are delivered exactly once across decode
                    replica death at every chunk boundary, and the
                    admission ledger drains to zero.
  shard_reslice     core/head_shards.py's ShardState.apply_assign /
                    dir_merge / replay_wal + ShardManager._reslice_locked:
                    a WAL'd mirror write racing shard SIGKILL, re-slice,
                    respawn-replay and a delayed stale assign — committed
                    dir entries survive, and no bucket is ever owned by
                    two shards at one epoch.
  job_ledger        core/jobs.py's JobLedger charge / settle / stop under
                    concurrent grant sites, a requeue re-charge and a
                    racing job-kill: charged usage never exceeds quota,
                    no task_id is ever charged twice concurrently, a
                    double settle releases exactly once, and a stopped
                    job admits nothing.

`run_all` splits the exploration budget across models; every violation
renders as one `interleaving-violation` Finding anchored at the module
that owns the core. These are hard failures — there is no baseline for a
protocol that loses a commit under some schedule.
"""

from __future__ import annotations

import os
import tempfile
import types

from tools.checklib import Finding
from tools.racecheck.interleave import explore

MODELS = {}


def model(name, path):
    def deco(fn):
        MODELS[name] = (fn, path)
        return fn
    return deco


# ---------------- lease protocol (runtime head side) ----------------


def _mk_spec(task_id: bytes, lease_seq: int, spill_hops: int = 0):
    from ray_tpu.core.task import TaskSpec
    spec = TaskSpec.__new__(TaskSpec)
    for s in TaskSpec.__slots__:
        try:
            setattr(spec, s, None)
        except AttributeError:
            pass
    spec.task_id = task_id
    spec.name = "racecheck"
    spec.lease_seq = lease_seq
    spec.spill_hops = spill_hops
    spec.max_retries = 3
    spec.retries_left = 3
    return spec


def _mk_head(api):
    """A harness head running the REAL lease bookkeeping methods."""
    from ray_tpu.core.jobs import JobLedger
    from ray_tpu.core.runtime import NodeState, Runtime
    head = types.SimpleNamespace()
    head.lock = api.lock(name="head.lock")
    head.nodes = {}
    head._reservations = {}
    # Real ledger: the lease pop funnels settle quota charges through it
    # (its own lock stays a real threading.Lock — ledger interleavings
    # get their own dedicated model below).
    head.jobs = JobLedger()
    head.lease_spills_total = 0
    head._hnat = None           # native head core absent in the model:
    # the (task_id, lease_seq) mirror pops are C-side bookkeeping with
    # no interleaving semantics of their own (idempotent erase)
    head.enqueued = []          # (task_id, lease_seq) of every requeue
    head.released = []          # tokens released
    head.task_events = types.SimpleNamespace(record=lambda *a, **k: None)
    # REAL protocol methods — the code under test.
    for name in ("_on_lease_return", "_on_lease_spilled",
                 "_find_lease_locked", "_pop_lease_locked"):
        setattr(head, name, types.MethodType(getattr(Runtime, name), head))
    # Effect edges, stubbed to count.
    head._release_token = lambda tok: (
        head.released.append(tok) if tok else None)

    def _enqueue_task_locked(spec, front=False):
        head.enqueued.append((spec.task_id, spec.lease_seq or 0))
        return True
    head._enqueue_task_locked = _enqueue_task_locked
    head._schedule = lambda: None

    def _on_lease_fail(nid, specs):
        # The dead-dest requeue path of _on_lease_spilled: same effect
        # shape as the real one — pop the reservation, requeue. (The
        # real method's retry accounting is out of scope here.)
        with head.lock:
            for spec in specs:
                head._release_token(
                    head._reservations.pop(spec.task_id, None))
                head._enqueue_task_locked(spec, front=True)
    head._on_lease_fail = _on_lease_fail

    def add_node(nid: bytes):
        n = NodeState(nid, {"CPU": 4.0}, None)
        head.nodes[nid] = n
        return n
    head.add_node = add_node
    return head


@model("lease_return", "ray_tpu/core/runtime.py")
def build_lease_return(api):
    """PR 2's fixed race, on the real methods: lease spilled A->B, B dies;
    the head's dead-dest requeue races the origin agent's lease_return
    fallback. Exactly one requeue, one token release — in EVERY order."""
    head = _mk_head(api)
    node_a = head.add_node(b"A")
    tid = b"T1"
    spec = _mk_spec(tid, lease_seq=1)
    node_a.leases[tid] = spec
    head._reservations[tid] = ("node", b"A", {"CPU": 1.0})

    def spilled_notice():
        api.point("head.lease_spilled.arrive")
        # B is not in head.nodes => dest dead => requeue path
        head._on_lease_spilled(b"A", [(tid, 1, 1, b"B")])

    def return_fallback():
        api.point("head.lease_return.arrive")
        head._on_lease_return(b"A", [_mk_spec(tid, lease_seq=1,
                                              spill_hops=1)])

    def check():
        assert len(head.enqueued) == 1, (
            f"duplicate execution: task requeued {len(head.enqueued)}x "
            f"({head.enqueued})")
        assert len(head.released) == 1, (
            f"reservation token released {len(head.released)}x")

    return {"threads": [("spill_notice", spilled_notice),
                        ("lease_return", return_fallback)],
            "check": check}


@model("lease_dedup", "ray_tpu/core/node_agent.py")
def build_lease_dedup(api):
    """Head re-drive racing the original grant delivery: the agent's
    (task_id, lease_seq) seen-set accepts exactly one copy; a RE-GRANT
    (bumped lease_seq) must still pass."""
    import collections
    from ray_tpu.core.node_agent import NodeAgent
    agent = types.SimpleNamespace()
    agent._lease_lock = api.lock(name="agent._lease_lock")
    agent._lease_seen = collections.OrderedDict()
    agent._lease_q = []
    agent._lease_dup_locked = types.MethodType(
        NodeAgent._lease_dup_locked, agent)

    def deliver(tag, seq):
        def fn():
            api.point(f"agent.grant.{tag}")
            spec = _mk_spec(b"T1", lease_seq=seq)
            with agent._lease_lock:
                if not agent._lease_dup_locked(spec):
                    agent._lease_q.append(spec)
        return fn

    def check():
        seqs = [s.lease_seq for s in agent._lease_q]
        assert sorted(seqs) == [1, 2], (
            f"dedup broke: queued lease_seqs {seqs} (want one seq-1 copy "
            "dropped, the seq-2 re-grant kept)")

    return {"threads": [("grant", deliver("orig", 1)),
                        ("redrive", deliver("redrive", 1)),
                        ("regrant", deliver("regrant", 2))],
            "check": check}


# ---------------- store write-reservation plane ----------------


@model("store_reserve", "ray_tpu/core/object_store.py")
def build_store_reserve(api):
    """The real native store's reservation protocol, Python seams under
    the scheduler (carve / bump-fill / publish / tail release / liveness
    sweep). Native calls are atomic steps; the interleavings explored are
    exactly the ones the _rsv_lock plane can produce."""
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_store import SharedMemoryStore

    path = os.path.join(
        tempfile.gettempdir(),
        f"rtpu_racecheck_{os.getpid()}_{next(_STORE_SEQ)}")
    store = SharedMemoryStore(path, size=4 << 20, num_slots=64,
                              create=True, num_shards=2)
    store.reservation_min_bytes = 1 << 10
    store.reservation_chunk_bytes = 64 << 10
    store._rsv_lock = api.lock(name="store._rsv_lock")
    sealed = []

    def writer(tag, n_objs):
        def fn():
            for i in range(n_objs):
                oid = ObjectID((tag + bytes([i])).ljust(16, b"\0"))
                api.point(f"store.put.{tag!r}.{i}")
                buf = store._acquire_buffer(oid, 4 << 10)
                buf.data[:4] = b"\xaa\xbb\xcc\xdd"
                if api.fired(f"store.abort.{tag!r}.{i}"):
                    buf.abort()   # abandoned put: chunk must free ONCE
                    continue
                buf.seal()
                sealed.append(oid)
        return fn

    def releaser():
        api.point("store.release_reservation")
        store.release_reservation()

    def sweeper():
        api.point("store.reclaim")
        # Live-owner safety: this process is alive, so the sweep may
        # reclaim NOTHING of the in-flight reservations.
        store.reclaim_orphans()

    def check():
        store.release_reservation()
        assert store.rsv_unused() == 0, (
            f"rsv_unused={store.rsv_unused()} after all tails "
            "released — a tail leaked or double-released")
        for oid in sealed:
            data, _meta = store.get_raw(oid, timeout=0)
            assert bytes(data[:4]) == b"\xaa\xbb\xcc\xdd", (
                f"sealed object {oid} unreadable after storm")
            store.release(oid)
        st = store.stats()
        assert st["num_objects"] == len(sealed), (
            f"{st['num_objects']} objects vs {len(sealed)} seals")

    def cleanup():
        store.close()
        try:
            os.unlink(path)
        except OSError:
            pass

    return {"threads": [("writer_a", writer(b"A", 2)),
                        ("writer_b", writer(b"B", 2)),
                        ("releaser", releaser),
                        ("sweeper", sweeper)],
            "check": check, "cleanup": cleanup}


def _counter():
    i = 0
    while True:
        yield i
        i += 1


_STORE_SEQ = _counter()


# ---------------- two-phase checkpoint commit ----------------


@model("ckpt_two_phase", "ray_tpu/train/checkpoint.py")
def build_ckpt_two_phase(api):
    """Real shard writes + real manifest commit (trainer._commit_if_ready)
    under rank death, manifest loss and a controller raise: the latest
    committed manifest never regresses, a torn dir is never resumable,
    and a commit that HAPPENED survives the controller's raise (PR 9)."""
    from ray_tpu.train import checkpoint as ckpt_mod
    from ray_tpu.train.trainer import _PendingCommit, JaxTrainer

    root = tempfile.mkdtemp(prefix="racecheck_ckpt_",
                            dir="/dev/shm" if os.path.isdir("/dev/shm")
                            else None)
    step = 7
    world = 2
    ckpt_dir = ckpt_mod.step_dir(root, step)
    acks_lock = api.lock(name="acks_lock")
    acks: dict[int, str] = {}

    ctl = types.SimpleNamespace()
    ctl._latest_committed = None
    ctl._ckpt_mgr = ckpt_mod.CheckpointManager(root, keep=2)
    ctl._commit_if_ready = types.MethodType(
        JaxTrainer._commit_if_ready, ctl)
    ctl.raised = False
    ctl.committed_before_raise = None

    def rank(r):
        def fn():
            api.point(f"rank{r}.step")
            name = ckpt_mod.write_shard({"rank": r, "step": step},
                                        ckpt_dir, r, world)
            api.point(f"rank{r}.durable")
            if api.fired(f"rank{r}.die_before_ack"):
                return  # the train.ckpt_shard_abandon window
            with acks_lock:
                acks[r] = name
        return fn

    def controller():
        pc = _PendingCommit(step, world)
        for _ in range(12):
            api.point("ctl.poll")
            with acks_lock:
                for r, name in acks.items():
                    pc.acks.add(r)
                    pc.shards[r] = name
            if ctl._commit_if_ready(pc, ckpt_dir, {}):
                # The PR 9 contract: the advance lands on the controller
                # IMMEDIATELY, so a raise below cannot lose it.
                ctl._latest_committed = ckpt_dir
                ctl.committed_before_raise = ckpt_dir
                break
            if api.fired("ctl.worker_death_raises"):
                # A dead rank raises out of the poll loop — fit()'s
                # FailurePolicy catches and restarts from
                # self._latest_committed.
                ctl.raised = True
                return
        return

    def check():
        # Restart-time recovery: exactly what fit() does.
        ckpt_mod.gc_uncommitted(root)
        latest = ckpt_mod.latest_committed(root)
        if ctl.committed_before_raise is not None:
            assert ctl._latest_committed == ckpt_dir, (
                "commit advance lost on the controller (the PR 9 "
                "lost-commit shape)")
            assert latest == ckpt_dir, (
                f"committed step invisible after restart: {latest}")
            m = ckpt_mod.load_manifest(latest)
            assert m["world_size"] == world and len(m["shards"]) == world
            for r in range(world):
                d = ckpt_mod.Checkpoint(latest).load_shard(r)
                assert d == {"rank": r, "step": step}
        else:
            assert latest is None, (
                f"uncommitted dir resumable after gc: {latest}")
            assert not os.path.exists(ckpt_dir), (
                "torn checkpoint dir survived gc_uncommitted")

    def cleanup():
        import shutil
        shutil.rmtree(root, ignore_errors=True)

    return {"threads": [("rank0", rank(0)), ("rank1", rank(1)),
                        ("controller", controller)],
            "check": check, "cleanup": cleanup}


# ---------------- serve stream-resume cursor ----------------


class _NoSleepBackoff:
    """Deterministic Backoff stand-in for the model: pacing is not the
    protocol under test, and real jittered sleeps would make schedules
    wall-time-dependent."""

    def __init__(self, *a, **k):
        self.left = 8

    def sleep(self):
        self.left -= 1
        return self.left > 0

    def reset(self):
        self.left = 8

    def expired(self):
        return self.left <= 0


@model("stream_resume", "ray_tpu/llm/serve.py")
def build_stream_resume(api):
    """Two concurrent streams through the REAL coordinator admission +
    recovery cursor, with a fake decode replica that honors
    decode_stream's contract (yields positions after `generated`) and can
    die at any chunk boundary: every position delivered exactly once,
    and the admission ledger drains to zero."""
    import collections

    from ray_tpu.core.status import RayTpuError
    from ray_tpu.llm import serve as serve_mod

    scripts = {"s1": [11, 12, 13, 14], "s2": [21, 22, 23]}

    coord = types.SimpleNamespace()
    coord.d = serve_mod.DisaggConfig(
        max_prefill_queue_tokens=1 << 20,
        max_decode_inflight_tokens=1 << 20,
        max_ongoing_requests=16, stream_chunk_tokens=2,
        handoff=False, dispatch_deadline_s=5.0, resume_deadline_s=5.0)
    coord._lock = api.lock(name="coord._lock")
    coord._prefill_queue_tokens = 0
    coord._decode_inflight_tokens = 0
    coord._ongoing = 0
    coord._tok_rate_ema = 0.0
    coord._n_decode_live = 1     # PR 14: decode budget is per live replica
    coord._shed_pending = 0
    coord._shed_reporting = False
    coord._local_decode = object()  # short-circuits the shed reporter
    coord._replica_load = {}
    coord._route_cache = {}
    coord._eos = -1
    coord.counters = collections.Counter()
    # REAL coordinator methods — the code under test.
    for name in ("_admit", "_release", "_release_prefill",
                 "_stream_tokens", "_run_admitted", "_unload"):
        setattr(coord, name,
                types.MethodType(
                    getattr(serve_mod._DisaggServerImpl, name), coord))
    coord._rep_id = serve_mod._DisaggServerImpl._rep_id  # staticmethod
    # Transport/effect stubs.
    coord._note_decode_failure = lambda rep, exc: None

    def _dispatch_decode(ids, cost):
        with coord._lock:
            coord._replica_load["rep"] = (
                coord._replica_load.get("rep", 0) + cost)
        return "rep"
    coord._dispatch_decode = _dispatch_decode

    def _prefill_with_retry(ids, temperature, top_p, top_k,
                            want_logp=False):
        script = scripts[bytes(ids).decode()]
        api.point("serve.prefill")
        return {"first": script[0], "kv": None, "kv_tokens": 0}
    coord._prefill_with_retry = _prefill_with_retry

    # Bounded faults (standard for schedule exploration): at most two
    # replica deaths per stream. Unbounded deaths exhaust the resume
    # deadline and the stream RIGHTFULLY errors out — by-design behavior,
    # not the exactly-once property under test.
    kills = {k: 0 for k in scripts}

    def _open_decode_stream(rep, ids, generated, kv, max_new,
                            temperature, top_p, top_k,
                            want_logp=False):
        key = bytes(ids).decode()
        script = scripts[key]
        pos = len(generated)
        assert pos >= 1, "resume cursor lost the prefill token"
        while pos < len(script):
            chunk = script[pos:pos + coord.d.stream_chunk_tokens]
            # Mirror the shipped chaos.kill placement: the replica dies
            # BEFORE the chunk reaches the consumer, taking it along.
            if kills[key] < 2 and api.fired("serve.decode.kill"):
                kills[key] += 1
                raise RayTpuError("decode replica died mid-stream")
            yield chunk
            pos += len(chunk)
    coord._open_decode_stream = _open_decode_stream

    results = {}

    def stream(key):
        def fn():
            script = scripts[key]
            ids = list(key.encode())
            cost = coord._admit(len(ids), len(script))
            toks, _lps = coord._run_admitted(ids, len(script), None, 1.0,
                                             0, cost)
            results[key] = toks
        return fn

    real_backoff = serve_mod.Backoff
    serve_mod.Backoff = _NoSleepBackoff

    def cleanup():
        serve_mod.Backoff = real_backoff

    def check():
        for key, script in scripts.items():
            assert results.get(key) == script, (
                f"stream {key}: delivered {results.get(key)} != {script} "
                "(re-emitted or skipped positions across replica death)")
        assert coord._ongoing == 0, f"_ongoing={coord._ongoing} leaked"
        assert coord._decode_inflight_tokens == 0, (
            f"decode budget leaked: {coord._decode_inflight_tokens}")
        assert coord._prefill_queue_tokens == 0, (
            f"prefill budget leaked: {coord._prefill_queue_tokens}")

    return {"threads": [("stream_s1", stream("s1")),
                        ("stream_s2", stream("s2"))],
            "check": check, "cleanup": cleanup}


# ---------------- head shard ownership / failover ----------------


@model("shard_reslice", "ray_tpu/core/head_shards.py")
def build_shard_reslice(api):
    """Shard failover on the real protocol core: a WAL'd directory write
    stream races the manager's kill-detect -> re-slice -> respawn-replay
    -> hand-back pass, plus a delayed duplicate assign frame. Invariants:
    (a) every dir entry whose WAL append RETURNED survives the SIGKILL
    via `replay_wal` (append-before-merge ordering), and (b) ownership
    stays epoch-gated — two shards at the same epoch never both own a
    bucket (`apply_assign` rejects stale epochs)."""
    from ray_tpu.core.head_shards import N_BUCKETS, ShardManager, ShardState

    class _Killed(Exception):
        """The shard process died: nothing past this point runs."""

    killed = [False]

    class _WalStore:
        """In-memory stand-in for the shard's persistence store: append()
        returning IS the commit point (the real store fsyncs a frame)."""

        def __init__(self, dies: bool = False):
            self.tables: dict = {}
            self.committed: list = []
            self.dies = dies

        def append(self, table, key, value):
            if self.dies and killed[0]:
                raise _Killed  # chaos seam sits BEFORE the WAL append
            self.tables.setdefault(table, {})[key] = value
            self.committed.append(key)

        def delete(self, table, key):
            self.tables.get(table, {}).pop(key, None)

        def load(self):
            return {t: dict(kv) for t, kv in self.tables.items()}

    def owned(sid):
        return [b for b in range(N_BUCKETS) if b % 2 == sid]

    wal0 = _WalStore(dies=True)
    shard0 = ShardState(0, wal0)
    shard0.lock = api.lock(name="shard0.lock")
    shard0.apply_assign(1, owned(0))
    shard1 = ShardState(1, _WalStore())
    shard1.lock = api.lock(name="shard1.lock")
    shard1.apply_assign(1, owned(1))

    mgr = types.SimpleNamespace()
    mgr.lock = api.lock(name="mgr.lock")
    mgr.n_shards = 2
    mgr.epoch = 1
    mgr.buckets = [i % 2 for i in range(N_BUCKETS)]
    mgr.links = {0: shard0, 1: shard1}  # _reslice_locked reads only keys
    mgr._reslice_locked = types.MethodType(
        ShardManager._reslice_locked, mgr)

    # Mirror writes aimed at shard-0 buckets (0, 2, 4 — all even).
    oids = [bytes([b]).ljust(16, b"x") for b in (0, 2, 4)]

    def dir_writer():
        for i, oid in enumerate(oids):
            api.point(f"shard0.dir_add.{i}")
            try:
                shard0.dir_merge([(oid, b"N1")])
            except _Killed:
                return  # un-acked frame: the flusher requeues it

    def heal():
        api.point("mgr.heal.detect")
        killed[0] = True  # the health pass saw the SIGKILL
        with mgr.lock:
            mgr.epoch += 1
            mgr.buckets = mgr._reslice_locked(0)
            survivor_owns = [b for b in range(N_BUCKETS)
                             if mgr.buckets[b] == 1]
            e = mgr.epoch
        shard1.apply_assign(e, survivor_owns)
        api.point("mgr.heal.respawn")
        s0 = ShardState(0, _WalStore())
        s0._store.tables = wal0.load()  # respawn on the same WAL path
        s0.lock = api.lock(name="shard0v2.lock")
        s0.replay_wal()
        mgr.links[0] = s0
        with mgr.lock:
            mgr.epoch += 1
            mgr.buckets = [0 if orig == 0 else cur for orig, cur in zip(
                [i % mgr.n_shards for i in range(N_BUCKETS)], mgr.buckets)]
            e = mgr.epoch
        s0.apply_assign(e, owned(0))
        shard1.apply_assign(e, owned(1))

    def stale_assign():
        # A delayed duplicate of the re-slice assign (epoch 2, survivor
        # owns everything) landing at ANY point — after the hand-back it
        # must bounce off the epoch gate, or two live shards both own
        # the even buckets.
        api.point("stale.assign.arrive")
        shard1.apply_assign(2, list(range(N_BUCKETS)))

    def check():
        s0, s1 = mgr.links[0], mgr.links[1]
        for oid in wal0.committed:
            assert oid in s0.dir, (
                f"committed dir entry {oid[:1]!r} lost across the shard "
                "SIGKILL (WAL append returned but replay missed it)")
        # Quiescent no-overlap: every assign (including the stale dup)
        # has landed, so the two LIVE shards' claims must be disjoint —
        # any overlap means the epoch gate let a stale frame through.
        both = s0.buckets & s1.buckets
        assert not both, (
            f"double ownership (epochs {s0.epoch}/{s1.epoch}): buckets "
            f"{sorted(both)} owned by shard 0 AND shard 1")
        assert len(mgr.buckets) == N_BUCKETS and all(
            sid in mgr.links for sid in mgr.buckets), (
            "manager bucket table names a shard without a live link")

    return {"threads": [("dir_writer", dir_writer),
                        ("heal", heal),
                        ("stale_assign", stale_assign)],
            "check": check}


# ---------------- job ledger quota gate ----------------


@model("job_ledger", "ray_tpu/core/jobs.py")
def build_job_ledger(api):
    """The REAL JobLedger under the scheduler: two grant sites racing on
    the same task_id (schedule-now vs lease refill), a requeue's
    settle+recharge cycle (with a deliberate double settle), and a job
    stop landing at any point. Invariants: at most one live charge per
    task_id, usage == sum of inflight charges (a double settle releases
    exactly once), usage never past quota, stopped jobs admit nothing."""
    from ray_tpu.core.jobs import JobLedger
    led = JobLedger(default_quota={"CPU": 2.0})
    led.lock = api.lock(name="jobs.lock")
    led.register("j")
    t1_grants: list[bool] = []
    post_stop: list[bool] = []

    def granter(tag):
        def fn():
            api.point(f"jobs.charge.{tag}")
            t1_grants.append(led.charge("j", b"T1", {"CPU": 1.0}))
        return fn

    def requeuer():
        api.point("jobs.charge.requeue")
        if not led.charge("j", b"T2", {"CPU": 2.0}):
            return
        api.point("jobs.settle.requeue")
        led.settle("j", b"T2")
        led.settle("j", b"T2")  # retry paths double-settle; must no-op
        api.point("jobs.recharge.requeue")
        led.charge("j", b"T2", {"CPU": 2.0})

    def stopper():
        api.point("jobs.stop")
        led.stop("j")
        post_stop.append(led.charge("j", b"T3", {"CPU": 0.5}))

    def check():
        rec = led.jobs["j"]
        assert sum(t1_grants) <= 1, (
            f"task T1 charged {sum(t1_grants)}x concurrently "
            "(double-grant guard broke)")
        assert post_stop == [False], (
            "a stopped job admitted a new charge")
        expect = 0.0
        for charged in rec.inflight.values():
            expect += charged.get("CPU", 0.0)
        assert abs(rec.usage["CPU"] - expect) < 1e-9, (
            f"usage {rec.usage['CPU']} != inflight sum {expect} "
            "(a settle leaked or released twice)")
        assert rec.usage["CPU"] <= 2.0 + 1e-9, (
            f"usage {rec.usage['CPU']} exceeds quota 2.0")

    return {"threads": [("grant_sched", granter("sched")),
                        ("grant_refill", granter("refill")),
                        ("requeue", requeuer),
                        ("job_kill", stopper)],
            "check": check}


# ---------------- driver ----------------


# Per-model exploration caps: the store/ckpt models do real (tmpfs) I/O
# per schedule, so their schedule counts stay low; the in-memory lease
# and cursor models can afford full bounded-exhaustive sweeps.
_CAPS = {
    "lease_return": dict(max_schedules=4000, pct_schedules=32),
    "lease_dedup": dict(max_schedules=4000, pct_schedules=32),
    "store_reserve": dict(max_schedules=250, pct_schedules=12,
                          max_preemptions=1),
    "ckpt_two_phase": dict(max_schedules=400, pct_schedules=16,
                           max_preemptions=1),
    "stream_resume": dict(max_schedules=2500, pct_schedules=24),
    "shard_reslice": dict(max_schedules=3000, pct_schedules=24),
    "job_ledger": dict(max_schedules=4000, pct_schedules=24),
}


def run_all(budget_s: float, seed: int = 0,
            names: tuple | None = None) -> list[Finding]:
    """Split the budget across models; one Finding per violation."""
    todo = [(n, MODELS[n]) for n in (names or MODELS) if n in MODELS]
    if not todo:
        return []
    per = max(budget_s / len(todo), 0.5)
    findings: list[Finding] = []
    for name, (build, path) in todo:
        caps = _CAPS.get(name, {})
        res = explore(build, seed=seed, budget_s=per, **caps)
        if res.violation is not None:
            findings.append(Finding(
                "interleaving-violation", path, 0,
                f"{name}: {res.violation} [schedule {res.schedule}, "
                f"after {res.schedules} schedules]",
                message=f"{name}: {res.violation}\n  schedule: "
                        f"{res.schedule}\n  trace:\n{res.trace}"))
    return findings
