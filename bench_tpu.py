#!/usr/bin/env python
"""TPU train-step benchmark: tokens/sec/chip and MFU on real hardware.

Runs the full jit-compiled train step (fwd + bwd + adamw) from
ray_tpu.train.step on two configs:
  - bench_125m (GPT-small geometry, the single-chip smoke config)
  - llama3_1b  (the largest config that trains on one 16 GB chip, remat on)
and reports tokens/sec/chip plus MFU% against the chip's peak bf16 FLOPs.

MFU uses the standard analytic model-FLOPs count (6N-style: 3x forward
matmul FLOPs incl. the causal-attention term at S/2 average context) — remat
recompute does NOT count, so remat configs under-report hardware utilization
by design.

Timing note: on the axon-tunneled backend, jax.Array.block_until_ready() does
not reliably synchronize; every measurement fences by fetching the scalar
loss to host.

Usage: python bench_tpu.py  -> one JSON line on stdout, detail on stderr.
Called by bench.py when a TPU is present.
"""

from __future__ import annotations

import json
import sys
import time

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,   # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,   # v6e / Trillium
    "v6e": 918e12,
}


def _peak_for(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return 197e12  # conservative default: v5e


def flops_per_token(c, seq: int) -> float:
    """Analytic train FLOPs/token: 3x forward (fwd + 2x bwd), causal
    attention at average context S/2."""
    d, ff, L = c.d_model, c.d_ff, c.n_layers
    attn_proj = (d * (c.n_heads * c.head_dim)
                 + 2 * d * (c.n_kv_heads * c.head_dim)
                 + (c.n_heads * c.head_dim) * d)
    if c.moe_experts:
        mlp = 3 * d * ff * c.moe_top_k
    else:
        mlp = 3 * d * ff
    per_fwd = (2 * (attn_proj + mlp) * L
               + 2 * d * c.vocab                       # lm head
               + 2 * 2 * (seq / 2) * d * L)            # causal attention
    return 3 * per_fwd


def bench_config(tag, config, batch, seq, steps=30):
    """Compile + run the train step; returns dict of metrics (or error).

    `steps` amortizes the single host fence: on the tunneled dev chip a
    device->host read costs ~100-200ms regardless of size, so per-step
    fencing would misreport MFU by tens of percent at small-model step
    times (dispatches are async and effectively free)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from ray_tpu.models.transformer import (init_params, loss_fn,
                                            param_logical_axes)
    from ray_tpu.train.step import make_train_step

    dev = jax.devices()[0]
    mesh = Mesh(np.array([dev]).reshape(1, 1, 1), ("dp", "fsdp", "tp"))
    params = init_params(config, jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    opt = optax.adamw(3e-4)
    init_fn, _, compile_for, _ = make_train_step(
        lambda p, b: loss_fn(p, b, config, mesh), opt, mesh,
        param_logical_axes(config))
    state = init_fn(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1),
                                0, config.vocab, jnp.int32)
    batch_d = {"tokens": tokens}
    step = compile_for(state, batch_d)

    t0 = time.time()
    state, loss = step(state, batch_d)
    compile_s = time.time() - t0
    _ = float(loss)  # host fence
    t0 = time.time()
    for _ in range(steps):
        state, loss = step(state, batch_d)
    final_loss = float(loss)  # host fence
    dt = (time.time() - t0) / steps

    tps = batch * seq / dt
    mfu = flops_per_token(config, seq) * tps / _peak_for(dev)
    out = {
        "config": tag, "params_m": round(n_params / 1e6, 1),
        "batch": batch, "seq": seq, "step_ms": round(dt * 1e3, 1),
        "tokens_per_sec_per_chip": round(tps),
        "mfu_pct": round(mfu * 100, 1),
        "compile_s": round(compile_s, 1), "loss": round(final_loss, 3),
    }
    print(f"{tag}: {out}", file=sys.stderr)
    return out


def bench_sp_ring(steps: int = 5, seq: int = 32768):
    """Long-context SP benchmark: ring-attention fwd+bwd at `seq` tokens
    through the Pallas flash kernels (VERDICT r2 #3). On one chip the ring
    degenerates to size 1 but exercises the full shard_map + kernel path;
    per-device memory stays O(kernel block) — the dense fallback this
    replaced would materialize a 32k x 32k score matrix per head."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from jax.sharding import Mesh
    from ray_tpu.parallel.ring_attention import ring_attention

    b, h, d = 1, 8, 128
    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs).reshape(n), ("sp",))
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, seq, h, d), jnp.bfloat16)
               for kk in keys)

    def loss(q, k, v):
        out = ring_attention(q, k, v, mesh, causal=True, impl="pallas")
        return jnp.sum(out.astype(jnp.float32) ** 2)

    grad_fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    t0 = time.time()
    g = grad_fn(q, k, v)
    _ = np.asarray(g[0][0, 0, 0, :1])  # host fence (axon: bur unreliable)
    compile_s = time.time() - t0
    t0 = time.time()
    for _ in range(steps):
        g = grad_fn(q, k, v)
        _ = np.asarray(g[0][0, 0, 0, :1])
    dt = (time.time() - t0) / steps

    # fwd = 2 matmuls, bwd = 7 (recompute x2, dp, ds.k, dpt, dv, dk);
    # causal halves the work.
    flops = 9 * 2 * b * h * seq * seq * d / 2
    out = {
        "config": f"sp_ring_{seq // 1024}k", "seq": seq,
        "ring_devices": n, "step_ms": round(dt * 1e3, 1),
        "tokens_per_sec": round(b * seq / dt),
        "attn_tflops": round(flops / dt / 1e12, 1),
        "compile_s": round(compile_s, 1),
    }
    print(f"sp_ring: {out}", file=sys.stderr)
    return out


def bench_llm_decode(layout: str, slots: int = 32, prompt_len: int = 128,
                     gen: int = 64):
    """Decode throughput at `slots` concurrent sequences (VERDICT r2 #2
    done-criterion): tokens/s through the continuous-batching engine with
    the given KV layout. Run for both layouts = the before/after."""
    import jax
    import numpy as np

    from ray_tpu.llm import EngineConfig, InferenceEngine
    from ray_tpu.models import configs

    cfg = configs.bench_125m()
    eng = InferenceEngine(
        cfg, EngineConfig(
            max_slots=slots, max_len=1024, prompt_buckets=(prompt_len,),
            eos_token=-1, kv_layout=layout),
        params=None, seed=0)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab, prompt_len - 1).tolist()
               for _ in range(slots)]
    # Warm: a throwaway generation pays every compile (admission, decode
    # windows) before the clock starts.
    eng.generate(prompts[:slots], max_new_tokens=gen, temperature=0.0)
    for p in prompts:
        eng.add_request(p, max_new_tokens=gen, temperature=0.0)
    t0 = time.time()
    before = sum(len(r.generated) for r in eng.finished.values())
    while eng.has_work():
        eng.step_window()
    toks = (sum(len(r.generated) for r in eng.finished.values())
            - before)
    dt = time.time() - t0
    out = {
        "config": f"llm_decode_{layout}", "slots": slots,
        "prompt_len": prompt_len, "max_new_tokens": gen,
        "decode_tokens_per_sec": round(toks / dt),
    }
    if layout == "paged":
        out["kv"] = eng.kv_stats()
    print(f"llm_decode[{layout}]: {out}", file=sys.stderr)
    return out


def bench_llm_prefix_shared(slots: int = 32, prompt_len: int = 256,
                            gen: int = 64):
    """Shared-prefix serving shape (VERDICT r3 #2 done-criterion:
    prefix_hits > 0 UNDER MEASUREMENT): every prompt shares a 128-token
    system-prompt prefix; admissions after the first borrow its cached
    pages and prefill only the unique tail."""
    import numpy as np

    from ray_tpu.llm import EngineConfig, InferenceEngine
    from ray_tpu.models import configs

    cfg = configs.bench_125m()
    eng = InferenceEngine(
        cfg, EngineConfig(
            max_slots=slots, max_len=1024,
            prompt_buckets=(128, 256), eos_token=-1, kv_layout="paged"),
        params=None, seed=0)
    rng = np.random.default_rng(0)
    shared = rng.integers(1, cfg.vocab, 128).tolist()
    prompts = [shared + rng.integers(1, cfg.vocab, prompt_len - 129).tolist()
               for _ in range(slots)]
    # Warm SEQUENTIALLY: the first generate registers the shared prefix
    # pages; the second burst (same size as the measured one, fresh
    # suffixes) compiles the batched prefix-hit prefill and the full-size
    # decode windows before the clock starts.
    eng.generate(prompts[:1], max_new_tokens=gen, temperature=0.0)
    warm = [shared + rng.integers(1, cfg.vocab, prompt_len - 129).tolist()
            for _ in range(slots)]
    eng.generate(warm, max_new_tokens=gen, temperature=0.0)
    for p in prompts:
        eng.add_request(p, max_new_tokens=gen, temperature=0.0)
    t0 = time.time()
    before = sum(len(r.generated) for r in eng.finished.values())
    while eng.has_work():
        eng.step_window()
    toks = sum(len(r.generated) for r in eng.finished.values()) - before
    dt = time.time() - t0
    out = {
        "config": "llm_decode_prefix_shared", "slots": slots,
        "prompt_len": prompt_len, "shared_prefix": 128,
        "max_new_tokens": gen,
        "decode_tokens_per_sec": round(toks / dt),
        "kv": eng.kv_stats(),
    }
    print(f"llm_prefix_shared: {out}", file=sys.stderr)
    return out


def bench_rl_ppo(iters: int = 3, env: str = "MinAtarBreakout-v0",
                 tag: str = "rl_ppo_minatar", num_envs: int = 16,
                 batch: int = 1024, mb: int = 256):
    """RL throughput (BASELINE north star metric "RLlib PPO env-steps/
    sec"). Two regimes:

    - gym envs (`MinAtar*`): host env stepping + CPU policy forwards,
      GAE + learner updates jit-compiled on the TPU — the reference's
      GPU-learner split (rllib/core/learner/) with XLA in the torch role.
    - `Jax*` envs: the WHOLE iteration (env dynamics + 84x84x4 frame
      rendering + rollout + GAE + minibatch epochs) is one compiled
      program on the TPU (rllib/core/ondevice.py); obs never leave the
      chip. `JaxAtariClassBreakout-v0` keeps the deepmind frame shape +
      nature-CNN of the reference's PPO-Atari benchmark, ROM-free."""
    from ray_tpu.rllib import PPOConfig

    config = (PPOConfig()
              .environment(env=env)
              .env_runners(num_env_runners=0,
                           num_envs_per_env_runner=num_envs,
                           rollout_fragment_length=64)
              .training(train_batch_size=batch, minibatch_size=mb,
                        num_epochs=2, lr=3e-4)
              .debugging(seed=0))
    algo = config.build_algo()
    try:
        algo.train()  # compile + warm
        t0 = time.time()
        steps0 = algo._timesteps
        learner_s = 0.0
        for _ in range(iters):
            lt0 = time.time()
            result = algo.train()
            learner_s += time.time() - lt0
        dt = time.time() - t0
        steps = algo._timesteps - steps0
        out = {
            "config": tag,
            "env": env,
            "env_steps_per_sec": round(steps / dt),
            "train_iter_ms": round(learner_s / iters * 1e3, 1),
            "sample_ms": result.get("sample_ms"),
            "learner_update_ms": result.get("learner_update_ms"),
            "policy_loss": round(float(result.get("policy_loss", 0.0)), 4),
        }
    finally:
        algo.stop()
    print(f"rl_ppo[{env}]: {out}", file=sys.stderr)
    return out


def bench_rl_impala(iters: int = 6, env: str = "JaxAtariClassBreakout-v0"):
    """IMPALA at the Atari benchmark shape, Anakin-style on-device
    (DeepMind's published TPU formulation): envs + V-trace + the update
    in one dispatch, behavior tree refreshed every broadcast_interval
    (BASELINE north star: "RLlib IMPALA multi-env async rollout -> TPU
    learner"; the async host path remains for gym envs and measured
    ~218 env-steps/s on this rig)."""
    import ray_tpu
    from ray_tpu.rllib import IMPALAConfig

    ray_tpu.init(num_cpus=3)
    try:
        config = (IMPALAConfig()
                  .environment(env=env)
                  .env_runners(num_env_runners=0,
                               num_envs_per_env_runner=16)
                  .training(train_batch_size=1024, minibatch_size=256,
                            lr=3e-4, broadcast_interval=2)
                  .debugging(seed=0))
        algo = config.build_algo()
        try:
            algo.train()  # compile + warm
            t0 = time.time()
            steps0 = algo._timesteps
            for _ in range(iters):
                result = algo.train()
            dt = time.time() - t0
            steps = algo._timesteps - steps0
            out = {
                "config": "rl_impala_atari_class",
                "env": env,
                "env_steps_per_sec": round(steps / dt),
                "train_iter_ms": round(dt / iters * 1e3, 1),
                "vtrace_policy_loss": round(
                    float(result.get("policy_loss", 0.0)), 4),
            }
        finally:
            algo.stop()
    finally:
        ray_tpu.shutdown()
    print(f"rl_impala[{env}]: {out}", file=sys.stderr)
    return out


def bench_llm_speculative(slots: int = 16, prompt_len: int = 128,
                          gen: int = 256):
    """Speculative decoding (VERDICT r4 #6 done-criterion: >=1.5x decode
    speedup at temperature 0 with acceptance stats). Repetitive prompts —
    the extractive/templated regime ngram speculation targets — decoded
    twice through identical engines, speculation off then on; both runs
    greedy, so outputs are token-identical and the speedup is pure
    verify-batching."""
    import numpy as np

    from ray_tpu.llm import EngineConfig, InferenceEngine
    from ray_tpu.models import configs

    cfg = configs.bench_125m()
    rng = np.random.default_rng(0)
    pattern = rng.integers(1, cfg.vocab, 16).tolist()
    prompts = []
    for i in range(slots):
        # repeated motif + tiny unique head: drafts accept once the model
        # locks into the motif
        prompts.append([int(rng.integers(1, cfg.vocab))]
                       + pattern * ((prompt_len - 2) // 16))

    def run_engine(speculation):
        eng = InferenceEngine(
            cfg, EngineConfig(
                max_slots=slots, max_len=1024,
                prompt_buckets=(prompt_len,), eos_token=-1,
                kv_layout="paged", speculation=speculation, spec_k=4),
            params=None, seed=0)
        eng.generate(prompts, max_new_tokens=gen, temperature=0.0)  # warm
        best = 0.0
        for _trial in range(2):  # best-of-2: tunnel RTT jitter is real
            for p in prompts:
                eng.add_request(p, max_new_tokens=gen, temperature=0.0)
            before = sum(len(r.generated) for r in eng.finished.values())
            t0 = time.time()
            while eng.has_work():
                eng.step_window()
            dt = time.time() - t0
            toks = (sum(len(r.generated) for r in eng.finished.values())
                    - before)
            best = max(best, toks / dt)
        return round(best), eng.kv_stats()

    plain_tps, _ = run_engine(None)
    spec_tps, st = run_engine("ngram")
    drafted = max(st.get("spec_drafted", 0), 1)
    out = {
        "config": "llm_decode_speculative", "slots": slots,
        "prompt_len": prompt_len, "max_new_tokens": gen, "spec_k": 4,
        "decode_tokens_per_sec": spec_tps,
        "plain_tokens_per_sec": plain_tps,
        "speedup": round(spec_tps / max(plain_tps, 1), 2),
        "acceptance_rate": round(st.get("spec_accepted", 0) / drafted, 3),
        "spec_drafted": st.get("spec_drafted", 0),
        "spec_accepted": st.get("spec_accepted", 0),
    }
    print(f"llm_speculative: {out}", file=sys.stderr)
    return out


def run(deadline: float | None = None, emit=None) -> dict:
    """Returns {"device": ..., "configs": [...]} or {"skipped": reason}.

    deadline is an absolute time.monotonic() bound: entries whose cost
    estimate doesn't fit are stamped "skipped" instead of run (r4's bench
    never got to print because late sections blew the driver budget).
    emit(tag, value) streams each headline number as it lands.
    """
    try:
        import jax
        dev = jax.devices()[0]
    except Exception as e:  # no accelerator runtime at all
        return {"skipped": f"jax init failed: {e}"}
    if dev.platform not in ("tpu", "axon"):
        return {"skipped": f"no TPU (platform={dev.platform})"}

    from ray_tpu.models import configs
    results = {"device": str(getattr(dev, "device_kind", dev)),
               "configs": []}
    # (tag, est_seconds, thunk) — estimates include tunnel compile time.
    # Ordered so the round's HEADLINE metrics land before the budget gate
    # starts skipping (estimates sum past the TPU budget by design;
    # skipped sections are stamped, never silently dropped).
    plan = [
        ("125m", 90,
         lambda: bench_config("125m", configs.bench_125m(attn_impl="pallas"),
                              16, 1024, steps=30)),
        ("llm_decode_paged", 80, lambda: bench_llm_decode("paged")),
        # Two full engines (spec off/on), warmed + best-of-2 measured
        # (~85s measured; headroom for cold compiles) — honest estimates
        # keep the budget gate meaningful (r4's gate failed on
        # underestimates).
        ("llm_decode_speculative", 150, bench_llm_speculative),
        # Same config as r4's host-path run (batch 1024 / mb 256 / 2
        # epochs / nature-CNN @ 84x84x4) with the env on-device:
        # 308 -> ~10,000 env-steps/s, learner 2509 -> ~100ms.
        ("rl_ppo_atari_class", 150,
         lambda: bench_rl_ppo(env="JaxAtariClassBreakout-v0",
                              tag="rl_ppo_atari_class", iters=8)),
        ("llama3_1b", 120,
         lambda: bench_config(
             "llama3_1b", configs.llama3_1b(attn_impl="pallas", remat=True),
             16, 1024, steps=10)),
        ("sp_ring_32k", 90, bench_sp_ring),
        ("llm_decode_prefix_shared", 80, bench_llm_prefix_shared),
        ("llm_decode_dense", 80, lambda: bench_llm_decode("dense")),
        ("rl_ppo_minatar", 60, bench_rl_ppo),
        # Scaled rollout (64 envs, batch 8192): ~59k env-steps/s.
        ("rl_ppo_atari_class_scaled", 150,
         lambda: bench_rl_ppo(env="JaxAtariClassBreakout-v0",
                              tag="rl_ppo_atari_class_scaled", iters=6,
                              num_envs=64, batch=8192, mb=512)),
        ("rl_impala_atari_class", 90, bench_rl_impala),
    ]
    for tag, est, thunk in plan:
        if deadline is not None and time.monotonic() + est > deadline:
            results["configs"].append({"config": tag, "skipped": "budget"})
            print(f"{tag}: skipped (budget)", file=sys.stderr)
            continue
        try:
            r = thunk()
            results["configs"].append(r)
            if emit is not None:
                for key in ("decode_tokens_per_sec", "tokens_per_sec",
                            "tokens_per_sec_per_chip", "env_steps_per_sec",
                            "mfu_pct"):
                    if isinstance(r, dict) and key in r:
                        emit(f"tpu_{tag}_{key}", float(r[key]))
                        break
        except Exception as e:
            results["configs"].append({"config": tag,
                                       "error": str(e)[:200]})
            print(f"{tag}: FAILED {e}", file=sys.stderr)
    return results


if __name__ == "__main__":
    print(json.dumps(run()))
